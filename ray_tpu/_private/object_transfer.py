"""Direct node-to-node object transfer, chunked, pooled and striped.

Reference: ``src/ray/object_manager/object_manager.h:117,206`` +
``object_buffer_pool.h`` — objects move between nodes in bounded chunks
directly between the object managers, with MULTIPLE transfers in flight;
the control plane (GCS) only brokers *locations*.  Here every node agent
(and the head, for its own store) runs an object server on its own TCP
listener; consumers (workers on other nodes, the driver, clients) dial it
and pull segments as streams of ≤1 MB chunks.  The head carries location
lookups only — never payload bytes.

Parallelism (the reference's in-flight chunk window,
``object_buffer_pool.h``): the puller keeps a small CONNECTION POOL per
peer store (``config.object_pool_size``, default 4).  Concurrent fetches
of different segments from one peer each ride their own pooled
connection, and a single large segment (≥ ``config.
object_stripe_threshold``, default 32 MB) is fetched as concurrent
byte-range STRIPES over several connections via the ``fetch_range`` verb.
Peers that only speak the original ``fetch`` verb (no ``fetch_range`` in
their advertised caps) are served by plain whole-segment streams — the
pool still parallelizes across segments.

Zero-copy receive: the receiver reserves its destination buffer up front
(a shm mapping via ``ShmStore.reserve_recv`` — see ``pull_to_segment``)
and ``recv_bytes_into``\\ s every chunk straight into it at its final
offset.  Receive is one copy end-to-end, like the send side (which
streams ``memoryview`` slices of the source mmap).

Write direction (direct puts; reference: plasma ``CreateObject``/
``Seal`` on the store socket): ``ObjectPusher`` streams a serialized
value INTO a peer's store through the same pooled connections — a
``reserve_put`` preallocates the PUBLIC destination segment (spill-aware
admission in the store), ``put_range`` stripes recv straight into the
mapping at final offsets, ``commit_put`` seals it.  The control plane
then carries only an O(1) ``put_commit`` descriptor registration.  All
put verbs ride the same CAPS advertisement as ``fetch_range``.
"""

from __future__ import annotations

import logging
import random
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import protocol, recovery, serialization
from ray_tpu._private.shm_store import _HEADER, _MAGIC


# Structured ObjectLostError fields from a segment name (one
# naming-rule implementation, recovery.py).
_seg_oid_hex = recovery.seg_oid_hex

logger = logging.getLogger(__name__)

CHUNK = 1 << 20  # 1 MB, the reference's object-manager chunk size

# Write-direction verbs (direct puts): a pusher streams a value's bytes
# into a reservation on the destination store.  Advertised together —
# a pusher engages only against peers declaring ALL of them.
PUT_CAPS: Tuple[str, ...] = ("reserve_put", "put_range", "commit_put",
                             "abort_put")

# Verbs this side's object server speaks beyond the original "fetch".
# Advertised out of band (agent_ready info / store_addr / client_ack
# replies) so pullers and pushers never probe a peer with a verb it
# would silently ignore.
CAPS: Tuple[str, ...] = ("fetch_range",) + PUT_CAPS


def peer_accepts_puts(caps) -> bool:
    """True when the peer's advertised verb set covers the whole direct-
    put lifecycle — the capability gate that keeps old-verb-only peers
    on the legacy ``put_parts`` path without ever seeing a new verb."""
    return all(v in caps for v in PUT_CAPS)


def _net_stall_timeout() -> float:
    """This process's zero-progress deadline for wire transfers; 0.0
    (never arm a deadline — the legacy fully-blocking behavior) with
    ``failure_detection`` off."""
    from ray_tpu._private.config import GLOBAL_CONFIG as _cfg

    return (_cfg.net_stall_timeout_s if _cfg.failure_detection else 0.0)


def net_params(cfg) -> Tuple[float, float, int, float]:
    """A Config -> the pool hosts' frozen failure-detection tuple
    (stall_timeout_s, connect_timeout_s, retry_count, backoff_base_ms);
    all-zero with the master switch off so nothing new ever runs."""
    if not cfg.failure_detection:
        return (0.0, 0.0, 0, 0.0)
    return (cfg.net_stall_timeout_s, cfg.net_connect_timeout_s,
            int(cfg.net_retry_count), cfg.net_retry_backoff_base_ms)

# Segment names whose metadata table failed to parse in _true_extent —
# each is logged once at debug level (bounded; see below).
_extent_fallbacks: set = set()


def _true_extent(view: memoryview, name: str = "?") -> int:
    """Bytes actually used by the segment — pooled reuse can leave a file
    up to ~2x the object (plus stale freed-object bytes); shipping the
    slack would waste network and receiver memory."""
    try:
        _magic, meta_len = _HEADER.unpack_from(view, 0)
        table = bytes(view[_HEADER.size:_HEADER.size + meta_len])
        offsets, lengths, _payload = serialization.loads_inline(table)
        end = _HEADER.size + meta_len
        for o, n in zip(offsets, lengths):
            end = max(end, o + n)
        return min(end, len(view))
    except Exception as e:  # noqa: BLE001 — fall back to whole-file extent
        # The fallback ships every byte of the file (incl. pool slack);
        # log once per segment so the wasted bytes are diagnosable.
        if name not in _extent_fallbacks:
            if len(_extent_fallbacks) > 4096:
                _extent_fallbacks.clear()
            _extent_fallbacks.add(name)
            logger.debug(
                "object_transfer: cannot parse segment table of %s "
                "(%r); shipping full file extent of %d bytes",
                name, e, len(view))
        return len(view)


def serve_connection(conn, store):
    """Agent-side loop for one consumer/producer connection: stream
    requested segments (or byte ranges of them) chunk by chunk
    (reference: ObjectManager::Push), and receive pushed puts into
    store reservations (reference: plasma CreateObject/Seal on the
    store socket).  ``reserved`` tracks reservations made on THIS
    connection so a pusher dying between ``reserve_put`` and
    ``commit_put`` (its socket closes) triggers the abort cleanup —
    no leaked segments, accounting restored."""
    reserved: set = set()
    try:
        while True:
            msg = protocol.recv(conn)
            if msg[0] == "fetch":
                name = msg[1]
                try:
                    seg = store.attach(name)
                except Exception as e:  # noqa: BLE001
                    protocol.send(conn, ("err", repr(e)))
                    continue
                try:
                    mv = memoryview(seg._mm)
                    total = _true_extent(mv, name)
                    protocol.send(conn, ("ok", total))
                    for off in range(0, total, CHUNK):
                        protocol.net_point("chunk_send", conn)
                        conn.send_bytes(mv[off:min(off + CHUNK, total)])
                finally:
                    del mv
                    seg.close()
            elif msg[0] == "fetch_range":
                # Byte-range stripe (clamped to the true extent).  The
                # reply carries BOTH the clamped stripe length and the
                # segment's total extent, so the first stripe doubles as
                # the size probe — no extra stat round trip.
                _tag, name, off, length = msg
                try:
                    seg = store.attach(name)
                except Exception as e:  # noqa: BLE001
                    protocol.send(conn, ("err", repr(e)))
                    continue
                try:
                    mv = memoryview(seg._mm)
                    total = _true_extent(mv, name)
                    off = min(max(0, off), total)
                    n = max(0, min(length, total - off))
                    protocol.send(conn, ("ok", n, total))
                    for o in range(off, off + n, CHUNK):
                        protocol.net_point("chunk_send", conn)
                        conn.send_bytes(mv[o:min(o + CHUNK, off + n)])
                finally:
                    del mv
                    seg.close()
            elif msg[0] == "reserve_put":
                # Direct-put reservation: preallocate the destination
                # mapping (public segment; spill-aware admission happens
                # in the store) and reply with its canonical name —
                # stripes and the commit address it by name, possibly
                # over OTHER pooled connections.
                _tag, oid_bin, total = msg
                try:
                    name = _puts_for(store).reserve(oid_bin, total)
                except Exception as e:  # noqa: BLE001
                    protocol.send(conn, ("err", repr(e)))
                    continue
                reserved.add(name)
                protocol.send(conn, ("ok", name))
            elif msg[0] == "put_range":
                # One byte-range stripe of a pending put: the payload
                # chunks following this message land straight in the
                # reserved mapping at their final offsets (socket ->
                # mmap, one copy).  The ack is the pusher's durability
                # signal for this range.
                _tag, name, off, length = msg
                if _puts_for(store).write(name, conn, off, length):
                    protocol.send(conn, ("ok", length))
                else:
                    protocol.send(conn, ("err",
                                         f"no pending put {name!r}"))
            elif msg[0] == "commit_put":
                name = msg[1]
                reserved.discard(name)
                try:
                    kind, ident, total = _puts_for(store).commit(name)
                except Exception as e:  # noqa: BLE001
                    protocol.send(conn, ("err", repr(e)))
                    continue
                protocol.send(conn, ("ok", kind, ident, total))
            elif msg[0] == "abort_put":
                reserved.discard(msg[1])
                _puts_for(store).abort(msg[1])
                protocol.send(conn, ("ok",))
            elif msg[0] == "close":
                return
    except (EOFError, OSError, TypeError):
        return
    finally:
        for name in reserved:
            # Reserving connection died/closed without commit: tear the
            # reservation down (pusher-death hygiene).
            try:
                _puts_for(store).abort(name)
            except Exception:
                pass
        try:
            conn.close()
        except Exception:
            pass


def accept_loop(listener, store, stopped, conn_name: str):
    """Shared object-server accept loop (node agents and the head run the
    identical one): accept, disable Nagle, and hand each consumer
    connection to its own ``serve_connection`` thread.  ``stopped`` is a
    callable polled so the owner's shutdown (which closes the listener)
    ends the loop."""
    while not stopped():
        try:
            conn = listener.accept()
            protocol.enable_nodelay(conn)
        except Exception:
            if stopped():
                return
            continue
        threading.Thread(target=serve_connection, args=(conn, store),
                         daemon=True, name=conn_name).start()


# One server-side put registry per store instance, shared by every
# consumer connection of that store's object server (reservation on one
# connection, stripes on others).  Keyed weakly so a retired store (agent
# re-registration) drops its registry with it.
_put_registries: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_put_registries_lock = threading.Lock()


def _puts_for(store) -> "PutRegistry":
    with _put_registries_lock:
        reg = _put_registries.get(store)
        if reg is None:
            reg = _put_registries[store] = PutRegistry(store)
        return reg


class PutRegistry:
    """Pending direct puts on ONE destination store (server side).

    A put's lifecycle spans multiple connections: ``reserve_put`` on the
    pusher's primary connection creates the entry, ``put_range`` stripes
    arrive on any pooled connection and recv straight into the shared
    mapping at disjoint offsets, ``commit_put``/``abort_put`` retire it.

    LOCK ORDER (checked by tests/test_lockcheck.py): ``_lock`` is an
    INDEPENDENT LEAF — it guards only the entry table and each entry's
    writer count / dead flag; reservation (file create), the stripe
    recv streaming, and the mapping teardown all run OUTSIDE it.  The
    writer count is what makes ``abort`` safe against in-flight stripes:
    the mapping is closed by the aborter only at writer count zero,
    else by the last draining writer.
    """

    def __init__(self, store):
        # weakref, NOT a strong reference: the registry is the VALUE in
        # a WeakKeyDictionary keyed by this store — a strong value->key
        # path would pin retired stores (and their registries) forever.
        # Pending reservations still legitimately pin the store through
        # their own PutReservation.store until resolved.
        self._store_ref = weakref.ref(store)
        self._lock = threading.Lock()  # lock-order: leaf
        self._pending: dict = {}  # name -> shm_store.PutReservation

    def reserve(self, oid_bin: bytes, total: int) -> str:
        store = self._store_ref()
        if store is None:
            raise OSError("destination store retired")
        res = store.reserve_put(oid_bin, total)
        with self._lock:
            if res.name in self._pending:
                dup = True
            else:
                dup = False
                self._pending[res.name] = res
        if dup:  # same object pushed twice concurrently: refuse the 2nd
            res.abort()
            raise ValueError(f"put already pending for {res.name}")
        return res.name

    def write(self, name: str, conn, off: int, length: int) -> bool:
        """Receive one stripe's payload into the reservation; returns
        False (after draining the payload, keeping the connection in
        sync) when the reservation is gone/dead or the range is out of
        bounds."""
        with self._lock:
            res = self._pending.get(name)
            if (res is None or res.dead or off < 0 or length < 0
                    or off + length > res.total):
                res = None
            else:
                res.writers += 1
        if res is None:
            _drain_discard(conn, length)
            return False
        # Zero-progress deadline while the stripe payload streams in: a
        # pusher that stalls mid-stripe errors this connection (the
        # serve loop's cleanup then aborts the reservation) instead of
        # wedging a server thread forever.  Cleared before the reply so
        # the connection's idle wait stays blocking.
        stall_t = _net_stall_timeout()
        if stall_t > 0:
            protocol.set_conn_deadline(conn, stall_t)
        try:
            view = memoryview(res.mm)
            try:
                _recv_range(conn, view, off, length)
            finally:
                del view
        finally:
            if stall_t > 0:
                try:
                    protocol.set_conn_deadline(conn, None)
                except OSError:
                    pass
            dispose = False
            with self._lock:
                res.writers -= 1
                if res.dead and res.writers == 0:
                    dispose = True
            if dispose:
                res.abort()
        return True

    def commit(self, name: str):
        with self._lock:
            res = self._pending.pop(name, None)
        if res is None:
            raise ValueError(f"no pending put {name!r}")
        res.commit()
        return res.kind, res.ident, res.total

    def abort(self, name: str) -> bool:
        """Tear down a pending reservation; returns True when one was
        found (its file/accounting teardown is owned by this call or —
        with stripes still draining — by the last writer)."""
        dispose = None
        with self._lock:
            res = self._pending.pop(name, None)
            if res is not None:
                if res.writers > 0:
                    res.dead = True  # last draining writer disposes
                else:
                    dispose = res
        if dispose is not None:
            dispose.abort()
        return res is not None


def _drain_discard(conn, n: int):
    """Consume and discard ``n`` payload bytes from a desynced-put
    stripe so the connection stays at a message boundary for the error
    reply.  Deadline-armed: a pusher that stalls mid-drain errors this
    connection (the serve loop's cleanup closes it) instead of wedging
    a server thread on a doomed stream."""
    from multiprocessing import BufferTooShort

    stall_t = _net_stall_timeout()
    if stall_t > 0:
        protocol.set_conn_deadline(conn, stall_t)
    scratch = bytearray(CHUNK)
    got = 0
    try:
        while got < n:
            try:
                got += conn.recv_bytes_into(scratch)  # noqa: RTL403 -- deadline armed above (legacy blocking with the switch off)
            except BufferTooShort as e:
                got += len(e.args[0])
    finally:
        if stall_t > 0:
            try:
                protocol.set_conn_deadline(conn, None)
            except OSError:
                pass


class _ConnPool:
    """Connections to ONE peer object server.

    The condition's lock guards only ``idle``/``total``/``closed`` —
    it is NEVER held across a dial or any stream I/O, so a connection
    mid-transfer cannot stall another thread's acquire/release.

    Failure isolation: ``evict`` closes ONLY the broken connection and
    decrements ``total`` under the condition, waking any waiter so it can
    dial a replacement — other pooled connections (and the threads
    streaming on them) are untouched.
    """

    __slots__ = ("addr", "authkey", "limit", "idle", "total", "cv",
                 "closed", "connect_timeout")

    def __init__(self, addr: str, authkey: bytes, limit: int,
                 connect_timeout: float = 0.0):
        self.addr = addr
        self.authkey = authkey
        self.limit = max(1, limit)
        self.idle: list = []
        self.total = 0
        self.cv = threading.Condition()
        self.closed = False
        # 0.0 = legacy unbounded dial (failure_detection off).
        self.connect_timeout = connect_timeout

    def acquire(self, timeout: Optional[float] = None):
        """An exclusive connection: a pooled idle one, a fresh dial while
        under the limit, else wait for a release/evict.  Returns None on
        timeout (stripe helpers give up and let the primary connection
        finish the job)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self.cv:
            while True:
                if self.closed:
                    raise OSError(f"connection pool to {self.addr} closed")
                if self.idle:
                    return self.idle.pop()
                if self.total < self.limit:
                    self.total += 1
                    break  # dial outside the condition
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    return None
                self.cv.wait(left)
        try:
            # Deadline-aware dial: connect timeout + SO_KEEPALIVE when
            # the failure-detection plane is on (a black-holed peer
            # fails the dial in net_connect_timeout_s instead of the
            # kernel's ~2 min default); the legacy Client() dial with
            # it off.
            conn = protocol.dial(protocol.parse_address(self.addr),
                                 authkey=self.authkey,
                                 connect_timeout=self.connect_timeout)
            return conn
        except BaseException:
            with self.cv:
                self.total -= 1
                self.cv.notify()
            raise

    def release(self, conn):
        close_it = False
        with self.cv:
            if self.closed:
                self.total -= 1
                close_it = True
            else:
                self.idle.append(conn)
            self.cv.notify()
        if close_it:
            try:
                conn.close()
            except Exception:
                pass

    def evict(self, conn):
        """Close ONLY this (broken) connection; waiters redial."""
        try:
            conn.close()
        except Exception:
            pass
        with self.cv:
            self.total -= 1
            self.cv.notify()

    def close(self):
        with self.cv:
            self.closed = True
            conns, self.idle = self.idle, []
            self.total -= len(conns)
            self.cv.notify_all()
        for conn in conns:
            try:
                protocol.send(conn, ("close",))
            except Exception:
                pass
            try:
                conn.close()
            except Exception:
                pass


class _PoolHost:
    """Per-peer connection-pool registry shared by the pull and push
    sides (ObjectPuller / ObjectPusher).

    LOCK ORDER (checked by tests/test_lockcheck.py via devtools.lockcheck):
    the registry ``_lock`` and every pool's condition lock are INDEPENDENT
    LEAVES — neither may be acquired while the other is held.  The
    registry lock guards only the ``_pools`` dict (lookup/insert/pop,
    never I/O and never a pool-condition acquire under it); a pool's
    condition guards only that pool's idle list and connection count and
    is never held across a dial or any stream I/O.  Streaming itself runs
    on an exclusively-acquired connection and holds NO lock at all — this
    is what lets N transfers to/from one peer proceed in parallel where
    the old design serialized them behind one per-connection lock held
    for the whole stream.
    """

    def __init__(self, authkey: bytes, pool_size: int,
                 net_config=None):
        self._authkey = authkey
        self._pool_size = pool_size
        self._pools: Dict[str, _ConnPool] = {}  # store_id -> pool
        self._lock = threading.Lock()  # lock-order: leaf
        # Failure-detection parameters, frozen at construction
        # (stall_timeout_s, connect_timeout_s, retry_count,
        # backoff_base_ms).  Default: this process's GLOBAL_CONFIG; the
        # head passes its _system_config explicitly.  All zero with the
        # switch off — no deadline is ever armed, no retry ever runs,
        # byte-identical legacy blocking transfers.
        if net_config is None:
            from ray_tpu._private.config import GLOBAL_CONFIG as _cfg

            net_config = net_params(_cfg)
        (self._stall_t, self._connect_t, self._net_retries,
         self._backoff_base_ms) = net_config

    def _pool_for(self, store_id: str, addr: str) -> _ConnPool:
        stale = None
        with self._lock:
            pool = self._pools.get(store_id)
            if pool is not None and pool.addr != addr:
                # Peer restarted on a new port: retire the old pool.
                stale, pool = pool, None
            if pool is None:
                pool = self._pools[store_id] = _ConnPool(
                    addr, self._authkey, self._pool_size,
                    connect_timeout=self._connect_t)
        if stale is not None:
            stale.close()
        return pool

    # ------------------------------------------- deadlines & retries --
    def _arm(self, conn):
        """Zero-progress deadline on an exclusively-acquired pooled
        connection for the duration of one transfer: every syscall gets
        ``net_stall_timeout_s`` to move bytes (progress resets the
        clock in the kernel), so a slow-but-moving stripe is never
        killed while a stalled one dies on time."""
        if self._stall_t > 0:
            protocol.set_conn_deadline(conn, self._stall_t)

    def _disarm(self, conn):
        """Clear the deadline before the connection returns to the pool
        (idle pooled connections must wait blocking, not time out)."""
        if self._stall_t > 0:
            try:
                protocol.set_conn_deadline(conn, None)
            except OSError:
                pass

    def _backoff(self, attempt: int):
        """Exponential backoff with jitter between transport retries —
        an in-lockstep retry storm against a recovering peer is its own
        failure mode."""
        base = self._backoff_base_ms / 1000.0
        delay = base * (2 ** (attempt - 1))
        time.sleep(delay * (1.0 + 0.5 * random.random()))

    def _run_with_net_retries(self, op, describe):
        """Run one transfer attempt function with the transport-retry
        policy: a zero-progress stall counts ``stall_timeouts``, evicts
        only the broken pooled connection (inside ``op``), and retries
        with backoff+jitter up to ``net_retry_count`` times
        (``net_retries``); exhaustion raises NetTimeoutError for the
        caller to wrap into its structured loss error.  Non-stall
        failures propagate untouched (they were never deadline
        trips)."""
        attempt = 0
        while True:
            try:
                return op()
            except BaseException as e:  # noqa: BLE001 -- stalls filtered, rest re-raised
                # A helper-stripe stall surfaces wrapped (_StripeError
                # from the pusher, the raw EAGAIN OSError re-raised from
                # the error list on the pull side): look one cause deep.
                if not (protocol.is_stall(e)
                        or (e.__cause__ is not None
                            and protocol.is_stall(e.__cause__))):
                    raise
                protocol.note_net_event("stall_timeouts")
                if attempt >= self._net_retries:
                    raise protocol.NetTimeoutError(
                        f"{describe} stalled past {self._stall_t}s "
                        f"({attempt} retr{'y' if attempt == 1 else 'ies'}"
                        f" exhausted)") from e
                attempt += 1
                protocol.note_net_event("net_retries")
                self._backoff(attempt)

    def drop(self, store_id: str):
        with self._lock:
            pool = self._pools.pop(store_id, None)
        if pool is not None:
            pool.close()

    def close(self):
        with self._lock:
            pools, self._pools = list(self._pools.values()), {}
        for pool in pools:
            pool.close()


class ObjectPuller(_PoolHost):
    """Consumer-side client: pooled connections to home-store object
    servers, pulling segments as chunk streams — whole segments or
    concurrent byte-range stripes (reference: ObjectManager::Pull +
    ObjectBufferPool chunk assembly with multiple chunks in flight).
    Lock conventions: see _PoolHost.
    """

    def __init__(self, authkey: bytes, pool_size: Optional[int] = None,
                 stripe_threshold: Optional[int] = None,
                 net_config=None):
        from ray_tpu._private.config import GLOBAL_CONFIG as _cfg

        super().__init__(authkey,
                         pool_size if pool_size is not None
                         else _cfg.object_pool_size,
                         net_config=net_config)
        self._stripe = (stripe_threshold if stripe_threshold is not None
                        else _cfg.object_stripe_threshold)

    # ------------------------------------------------------------ fetch --
    def fetch(self, store_id: str, addr: str, name: str, sink=None,
              caps: Tuple[str, ...] = ()):
        """The raw segment bytes, pulled in CHUNK pieces.

        ``sink(total)`` supplies the destination buffer once the size is
        known (default: a fresh ``bytearray``) — pass a shm mapping for a
        one-copy receive (``pull_to_segment``).  ``caps`` is the peer's
        advertised verb set: with ``"fetch_range"`` present, a segment at
        least the stripe threshold long arrives as concurrent byte-range
        stripes over several pooled connections.  Returns the filled
        buffer.

        Failure detection: every attempt runs under the zero-progress
        stall deadline; a stall evicts only the broken pooled connection
        and the fetch retries (backoff+jitter, ``net_retries``) before
        surfacing a structured, reconstructable
        ``ObjectLostError(phase="stalled")`` — the caller then hedges to
        its existing getparts/relay fallback and ultimately to lineage
        reconstruction.  A timeout is never a hang."""
        try:
            return self._run_with_net_retries(
                lambda: self._fetch_attempt(store_id, addr, name, sink,
                                            caps),
                f"pull of {name} from {store_id}")
        except protocol.NetTimeoutError as e:
            from ray_tpu import exceptions as exc

            raise exc.ObjectLostError(
                f"segment {name} stalled at {store_id}: {e}",
                object_id=_seg_oid_hex(name), home=store_id,
                phase="stalled") from e

    def _fetch_attempt(self, store_id: str, addr: str, name: str, sink,
                       caps: Tuple[str, ...]):
        pool = self._pool_for(store_id, addr)
        conn = pool.acquire()
        self._arm(conn)
        try:
            if "fetch_range" in caps and self._stripe > 0:
                buf = self._fetch_striped(pool, conn, store_id, name, sink)
            else:
                buf = self._fetch_whole(conn, store_id, name, sink)
        except BaseException:
            # Evict ONLY this connection (a peer error reply leaves the
            # stream positioned at the next request, but a transport or
            # mid-stream failure leaves it desynced — close it either
            # way; redial is cheap and rare).  Concurrent fetches on the
            # pool's other connections are unaffected.
            pool.evict(conn)
            raise
        self._disarm(conn)
        pool.release(conn)
        return buf

    def _fetch_whole(self, conn, store_id: str, name: str, sink):
        protocol.send(conn, ("fetch", name))
        reply = protocol.recv(conn)
        if reply[0] != "ok":
            from ray_tpu import exceptions as exc

            raise exc.ObjectLostError(
                f"segment {name} unreadable at {store_id}: {reply[1]}",
                object_id=_seg_oid_hex(name), home=store_id,
                phase="pull")
        total = reply[1]
        buf = bytearray(total) if sink is None else sink(total)
        view = memoryview(buf)
        _recv_range(conn, view, 0, total)
        return buf

    def _fetch_striped(self, pool: _ConnPool, conn, store_id: str,
                      name: str, sink):
        """Whole segment via byte-range requests: the first request is
        both size probe and first stripe; anything beyond it is split
        into stripe-sized ranges drained by this thread AND helper
        threads on additional pooled connections."""
        from ray_tpu import exceptions as exc

        stripe = self._stripe
        protocol.send(conn, ("fetch_range", name, 0, stripe))
        reply = protocol.recv(conn)
        if reply[0] != "ok":
            raise exc.ObjectLostError(
                f"segment {name} unreadable at {store_id}: {reply[1]}",
                object_id=_seg_oid_hex(name), home=store_id,
                phase="pull")
        _tag, first_n, total = reply
        buf = bytearray(total) if sink is None else sink(total)
        view = memoryview(buf)
        _recv_range(conn, view, 0, first_n)
        if first_n >= total:
            return buf

        ranges = deque((off, min(stripe, total - off))
                       for off in range(first_n, total, stripe))
        errors: list = []

        def drain(c):
            while not errors:
                try:
                    off, length = ranges.popleft()
                except IndexError:
                    return
                protocol.send(c, ("fetch_range", name, off, length))
                r = protocol.recv(c)
                if r[0] != "ok" or r[1] != length:
                    raise exc.ObjectLostError(
                        f"segment {name} changed mid-stripe at "
                        f"{store_id}: {r!r}",
                        object_id=_seg_oid_hex(name), home=store_id,
                        phase="pull")
                _recv_range(c, view, off, length)

        def helper():
            # A busy pool is not an error: give up quickly and let the
            # primary connection finish the remaining ranges.
            try:
                c = pool.acquire(timeout=0.25)
            except OSError:
                return
            if c is None:
                return
            self._arm(c)
            try:
                drain(c)
            except BaseException as e:  # noqa: BLE001 — joined below
                errors.append(e)
                pool.evict(c)
                return
            self._disarm(c)
            pool.release(c)

        helpers = [
            threading.Thread(target=helper, daemon=True,
                             name="rtpu-stripe")
            for _ in range(min(len(ranges), self._pool_size - 1))
        ]
        for t in helpers:
            t.start()
        try:
            drain(conn)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            # Signal the helpers (their loop checks ``errors``) so they
            # stop at their next range instead of streaming the rest of
            # a doomed transfer; failure propagates after the join.
            errors.append(e)
            raise
        finally:
            for t in helpers:
                t.join()
        if errors:
            raise errors[0]
        return buf


class PutUnsupportedError(RuntimeError):
    """The destination's advertised caps lack the put verbs — the caller
    keeps the legacy ``put_parts`` control-plane path (never probed)."""


class _StripeError(Exception):
    """A HELPER stripe connection failed; the primary connection is at a
    message boundary (safe to send ``abort_put`` on it)."""


class ObjectPusher(_PoolHost):
    """Producer-side twin of ObjectPuller: stream a serialized value
    straight into a reservation on the destination store's object server
    — whole on one pooled connection, or as concurrent byte-range
    stripes over several (reference: plasma CreateObject/Seal through
    the store socket; writes never ride the control plane).

    The pusher computes the destination segment's exact on-disk image
    locally (``shm_store.segment_layout`` — header+table+aligned
    buffers) and streams byte ranges of that LOGICAL image without ever
    materializing it: each range walks the source buffer views, with
    alignment/padding gaps sent as zeros.  One copy end-to-end
    (source buffer -> socket -> destination mmap).

    Failure hygiene mirrors the pull side: a mid-stream error evicts
    ONLY the broken pooled connection; a reservation whose push failed
    is aborted — explicitly via ``abort_put`` when the primary
    connection is at a message boundary, else implicitly by the server's
    reserving-connection-close cleanup.  Lock conventions: _PoolHost.
    """

    def __init__(self, authkey: bytes, pool_size: Optional[int] = None,
                 stripe_threshold: Optional[int] = None,
                 net_config=None):
        from ray_tpu._private.config import GLOBAL_CONFIG as _cfg

        super().__init__(authkey,
                         pool_size if pool_size is not None
                         else (_cfg.object_put_pool_size
                               or _cfg.object_pool_size),
                         net_config=net_config)
        self._stripe = (stripe_threshold if stripe_threshold is not None
                        else _cfg.object_put_stripe_threshold)

    def push(self, store_id: str, addr: str, oid_bin: bytes, meta,
             buffers, caps: Tuple[str, ...] = (),
             stripe_threshold: Optional[int] = None):
        """Push one serialized value (``meta`` + out-of-band buffer
        views) into ``store_id``'s store; returns ``(kind, ident,
        total)`` — kind ``"shm"``/``"spilled"``, ident the segment name
        or spill path, total the committed byte size — for the caller's
        ``("put_commit", ...)`` control message.  Raises
        PutUnsupportedError (without any wire traffic) when the peer
        does not advertise the put verbs.

        The put verbs double as the serving tier's chain-handoff wire
        protocol: a prefill replica streams a finished KV block chain
        (contiguous block pages + pickled block table, laid out by
        ``segment_layout``) into the decode replica's node store with
        exactly this ``reserve_put`` → ``put_range``* → ``commit_put``
        sequence, and the decode side attaches the committed segment by
        the returned ident.  ``stripe_threshold`` overrides the pusher's
        configured stripe cutover for one call — chain images are
        typically much larger than task args, so that path stripes
        earlier (``kv_stream_stripe_threshold``).

        Failure detection mirrors the pull side: attempts run under the
        zero-progress stall deadline and retry with backoff+jitter; a
        retry's fresh ``reserve_put`` is safe because the evicted
        reserving connection's close already triggered the server-side
        abort cleanup (the backoff gives it time to land) — the same
        cleanup that aborts a half-received chain when a prefill
        replica dies mid-stream.  Exhaustion raises NetTimeoutError —
        every caller already treats any push failure as "fall back to
        the legacy put_parts path"."""
        if not peer_accepts_puts(caps):
            raise PutUnsupportedError(
                f"peer {store_id} does not speak the put verbs")
        from ray_tpu._private.shm_store import segment_layout

        meta = bytes(meta)
        table, offsets, total = segment_layout(meta, buffers)
        head = bytearray(_HEADER.size)
        _HEADER.pack_into(head, 0, _MAGIC, len(table))
        # Header and table as separate pieces: for a buffer-less value
        # the whole meta lives in the (multi-MB) table pickle, and
        # concatenating would copy it once more before streaming.
        pieces = [(0, memoryview(head)), (_HEADER.size, memoryview(table))]
        pieces += [(off, memoryview(b).cast("B"))
                   for off, b in zip(offsets, buffers)]
        return self._run_with_net_retries(
            lambda: self._push_attempt(store_id, addr, oid_bin, pieces,
                                       total, stripe=stripe_threshold),
            f"push of {oid_bin.hex()[:12]} to {store_id}")

    def _push_attempt(self, store_id: str, addr: str, oid_bin: bytes,
                      pieces, total: int, stripe: Optional[int] = None):
        pool = self._pool_for(store_id, addr)
        conn = pool.acquire()
        self._arm(conn)
        name = None
        boundary = True  # primary conn at a message boundary?
        try:
            protocol.send(conn, ("reserve_put", oid_bin, total))
            reply = protocol.recv(conn)
            if reply[0] != "ok":
                raise OSError(f"put refused by {store_id}: {reply!r}")
            name = reply[1]
            if stripe is None:
                stripe = self._stripe
            try:
                boundary = False
                if stripe > 0 and total > stripe:
                    self._push_striped(pool, conn, name, pieces, total,
                                       stripe)
                else:
                    _push_range(conn, name, pieces, 0, total)
                boundary = True
            except _StripeError:
                boundary = True  # helpers failed; primary drained clean
                raise
            protocol.send(conn, ("commit_put", name))
            reply = protocol.recv(conn)
            if reply[0] != "ok":
                raise OSError(f"put commit failed at {store_id}: "
                              f"{reply!r}")
            kind, ident, size = reply[1], reply[2], reply[3]
        except BaseException:
            # Best-effort explicit abort when the primary stream is at a
            # message boundary; otherwise evicting the (reserving)
            # connection makes the server's close-cleanup abort it.
            if name is not None and boundary:
                try:
                    protocol.send(conn, ("abort_put", name))
                    protocol.recv(conn)
                except Exception:
                    pass
            pool.evict(conn)
            raise
        self._disarm(conn)
        pool.release(conn)
        return kind, ident, size

    def _push_striped(self, pool: _ConnPool, conn, name: str, pieces,
                      total: int, stripe: int):
        """Concurrent byte-range stripes: this thread drains ranges on
        the primary connection; helpers drain on additional pooled
        connections (same shape as ObjectPuller._fetch_striped, pointed
        the other way)."""
        ranges = deque((off, min(stripe, total - off))
                       for off in range(0, total, stripe))
        errors: list = []

        def drain(c):
            while not errors:
                try:
                    off, length = ranges.popleft()
                except IndexError:
                    return
                _push_range(c, name, pieces, off, length)

        def helper():
            # A busy pool is not an error: give up quickly and let the
            # primary connection finish the remaining ranges.
            try:
                c = pool.acquire(timeout=0.25)
            except OSError:
                return
            if c is None:
                return
            self._arm(c)
            try:
                drain(c)
            except BaseException as e:  # noqa: BLE001 — joined below
                errors.append(e)
                pool.evict(c)
                return
            self._disarm(c)
            pool.release(c)

        helpers = [
            threading.Thread(target=helper, daemon=True,
                             name="rtpu-put-stripe")
            for _ in range(min(len(ranges) - 1, self._pool_size - 1))
        ]
        for t in helpers:
            t.start()
        try:
            drain(conn)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errors.append(e)  # helpers stop at their next range
            raise
        finally:
            for t in helpers:
                t.join()
        if errors:
            # Primary drained clean (or we'd have raised above): wrap so
            # the caller knows an explicit abort_put is safe.
            raise _StripeError() from errors[0]


_ZEROS = bytes(1 << 14)


def _push_range(conn, name: str, pieces, off: int, n: int):
    """One put_range exchange: header, exactly ``n`` payload bytes of
    the logical segment image in ≤CHUNK messages, ack."""
    protocol.send(conn, ("put_range", name, off, n))
    _send_piece_range(conn, pieces, off, n)
    reply = protocol.recv(conn)
    if reply[0] != "ok" or reply[1] != n:
        raise OSError(f"put_range [{off}, {off + n}) of {name} refused: "
                      f"{reply!r}")


def _send_piece_range(conn, pieces, off: int, n: int):
    """Stream bytes [off, off+n) of the logical segment image.
    ``pieces`` is a sorted list of (offset, memoryview); bytes covered
    by no piece (alignment gaps, table padding) are zeros."""
    end = off + n
    pos = off
    for poff, view in pieces:
        plen = len(view)
        if poff + plen <= pos:
            continue
        if poff >= end:
            break
        if poff > pos:
            _send_zeros(conn, poff - pos)
            pos = poff
        lo = pos - poff
        hi = min(plen, end - poff)
        for o in range(lo, hi, CHUNK):
            protocol.net_point("chunk_send", conn)
            conn.send_bytes(view[o:min(o + CHUNK, hi)])
        pos = poff + hi
        if pos >= end:
            break
    if pos < end:
        _send_zeros(conn, end - pos)


def _send_zeros(conn, n: int):
    while n > 0:
        m = min(n, len(_ZEROS))
        conn.send_bytes(_ZEROS if m == len(_ZEROS) else _ZEROS[:m])
        n -= m


def _recv_range(conn, view: memoryview, off: int, n: int):
    """Receive exactly ``n`` chunk messages' worth of bytes straight into
    ``view`` at ``off`` (one copy: socket -> destination buffer)."""
    got = 0
    while got < n:
        # Chaos syncpoint (one global None-check when unarmed): a
        # RAY_TPU_CHAOS rule can kill this process deterministically
        # mid-stream — the chaos battery's "die during a striped pull".
        recovery.syncpoint("pull_chunk")
        got += conn.recv_bytes_into(view, off + got)  # noqa: RTL403 -- zero-progress deadline armed by every caller (_PoolHost._arm / recv_parts) before the loop
    if got != n:
        raise OSError(
            f"object stream desync: got {got} bytes for a {n}-byte range")


def pull_to_segment(puller: ObjectPuller, store, store_id: str, addr: str,
                    name: str, caps: Tuple[str, ...] = ()):
    """Pull ``name`` from a remote object server straight into a local shm
    mapping and return it as a read ``Segment`` — the one-copy receive
    path (socket -> mmap; deserialization then builds zero-copy views over
    the mapping).  Uses ``ShmStore.reserve_recv``/``commit_recv``; the
    reservation is aborted on any failure.  When the store cannot host the
    reservation (capacity gate, tmpfs full), the receive degrades to a
    heap buffer — the transfer still completes one-copy, it just doesn't
    live in shm."""
    from ray_tpu._private.shm_store import Segment

    state: dict = {}

    def sink(total: int):
        if state.get("reserved"):
            # A transport retry re-invokes the sink: release the failed
            # attempt's reservation before making a fresh one.
            try:
                store.abort_recv(state["buf"])
            except Exception:
                pass
        state["total"] = total
        try:
            buf = store.reserve_recv(name, total)
            state["reserved"] = True
        except (MemoryError, ValueError, OSError):
            buf = bytearray(total)
            state["reserved"] = False
        state["buf"] = buf
        return buf

    try:
        puller.fetch(store_id, addr, name, sink=sink, caps=caps)
    except BaseException:
        if state.get("reserved"):
            store.abort_recv(state["buf"])
        raise
    if state.get("reserved"):
        return store.commit_recv(name, state["buf"], state["total"])
    return Segment(name, "", state["total"], state["buf"])


class _PullEntry:
    """One in-flight (or retained prefetched) pull of a remote segment."""

    __slots__ = ("event", "seg", "failed", "prefetch", "size", "evicted",
                 "retained_at")

    def __init__(self, prefetch: bool):
        self.event = threading.Event()
        self.seg = None          # Segment once the pull completed
        self.failed = False
        self.prefetch = prefetch  # started by the prefetcher (not a task)
        self.size = 0
        self.evicted = False     # retention cap/TTL closed the segment
        self.retained_at = 0.0   # monotonic retain time (TTL sweep)

    def wait(self, timeout: Optional[float] = None):
        """The pulled Segment, or None when the leader's pull failed (the
        waiter then runs its own fallback path)."""
        if not self.event.wait(timeout):
            return None
        return None if self.failed else self.seg


class PullRegistry:
    """Per-process singleflight registry for remote-segment pulls.

    N concurrent materializations of the same remote segment (executing
    tasks + the argument prefetcher) share ONE pull: the first caller
    becomes the leader and streams the bytes; everyone else attaches to
    its entry and consumes the same received Segment (segments received
    via ``reserve_recv`` are process-private mappings, so sharing one
    read-only Segment between consumers in this process is safe).  A
    failed pull wakes every waiter with None — each then falls back to
    its own existing path (redial / head relay).

    Prefetched pulls are RETAINED (state DONE) until a task's
    ``_load_args`` consumes them or the retention cap evicts them
    (evictions count as ``prefetch_waste_bytes`` — bytes pulled for a
    task that never ran here, e.g. stolen back by the head).

    Reference: the raylet's local pull manager dedup — one
    ``ObjectManager::Pull`` per object regardless of how many queued
    tasks depend on it (pull_manager.h).

    LOCK ORDER (checked by tests/test_lockcheck.py): ``_lock`` is an
    INDEPENDENT LEAF — it guards only the entry dict and the counters,
    is never held across a dial, any stream I/O, or an event wait, and
    no other lock is ever acquired under it.
    """

    # Completed prefetched segments retained for consumption; past either
    # bound the oldest unconsumed one is evicted (counted as waste).  The
    # byte budget keeps a burst of large prefetched-then-stolen args from
    # pinning unbounded shm on the worker, and the TTL sweep (driven by
    # the worker's periodic flusher) reclaims stragglers whose task never
    # ran here even if no further prefetch ever fires.
    RETAIN_CAP = 32
    RETAIN_BYTES = 256 << 20
    RETAIN_TTL_S = 10.0

    def __init__(self):
        self._lock = threading.Lock()  # lock-order: leaf
        self._inflight: Dict[tuple, _PullEntry] = {}
        self._retained: "deque[tuple]" = deque()  # FIFO of DONE keys
        self._retained_bytes = 0
        self.deduped_pulls = 0       # waiters that shared a leader's pull
        self.prefetch_hit_bytes = 0  # prefetched bytes a task consumed
        self.prefetch_waste_bytes = 0  # prefetched bytes never consumed

    def begin(self, key: tuple,
              prefetch: bool = False) -> Tuple[_PullEntry, bool]:
        """Join or start the pull for ``key``; returns (entry, is_leader).

        A non-leader either waits on ``entry.wait()`` (pull in flight) or
        finds ``entry.event`` already set (a retained prefetched
        segment); task-path callers then :meth:`take` the entry to
        consume it."""
        with self._lock:
            ent = self._inflight.get(key)
            if ent is not None:
                if not ent.event.is_set() and not prefetch:
                    self.deduped_pulls += 1
                return ent, False
            ent = _PullEntry(prefetch)
            self._inflight[key] = ent
            return ent, True

    def take(self, key: tuple, ent: _PullEntry):
        """Consume a DONE entry's segment for task materialization (pops
        retained prefetches and credits the hit).  Returns None when the
        retention cap evicted (and closed) the segment between the
        caller's begin() and now — the caller re-pulls directly
        (_pull_remote_segment retries as a fresh leader)."""
        with self._lock:
            if ent.evicted:
                return None
            cur = self._inflight.get(key)
            if cur is ent and ent.event.is_set():
                self._inflight.pop(key, None)
                try:
                    self._retained.remove(key)
                    self._retained_bytes -= ent.size
                except ValueError:
                    pass
                if ent.prefetch and not ent.failed:
                    self.prefetch_hit_bytes += ent.size
        return None if ent.failed else ent.seg

    def finish(self, key: tuple, ent: _PullEntry, seg, *,
               retain: bool = False):
        """Leader completion: publish the result and wake waiters.  With
        ``retain`` (prefetch), a successful pull stays registered as DONE
        until consumed or evicted."""
        evicted = []
        with self._lock:
            ent.seg = seg
            ent.failed = seg is None
            if seg is not None:
                ent.size = getattr(seg, "size", 0)
            if retain and seg is not None:
                ent.retained_at = time.monotonic()
                self._retained.append(key)
                self._retained_bytes += ent.size
                while self._retained and (
                        len(self._retained) > self.RETAIN_CAP
                        or self._retained_bytes > self.RETAIN_BYTES):
                    old = self._retained.popleft()
                    old_ent = self._inflight.pop(old, None)
                    if old_ent is not None:
                        # Flagged under the lock; a concurrent take()
                        # checks it under the same lock, so nobody can
                        # receive the segment we close below.
                        old_ent.evicted = True
                        self._retained_bytes -= old_ent.size
                        self.prefetch_waste_bytes += old_ent.size
                        evicted.append(old_ent)
            else:
                self._inflight.pop(key, None)
        # Outside _lock (leaf discipline): Event.set acquires the event's
        # internal condition lock.  The result fields were published under
        # _lock above, so woken waiters read them consistently.
        ent.event.set()
        for old_ent in evicted:
            if old_ent.seg is not None:
                old_ent.seg.close()

    def sweep(self):
        """Evict retained prefetched segments older than RETAIN_TTL_S.
        Without this, a worker whose prefetched tasks were stolen back
        (and that never prefetches again) would pin up to RETAIN_BYTES of
        shm mappings until process exit — the FIFO eviction loop only
        runs on later retains.  Called from the worker's periodic
        flusher; retain order is FIFO, so the scan stops at the first
        young entry."""
        now = time.monotonic()
        evicted = []
        with self._lock:
            while self._retained:
                key = self._retained[0]
                ent = self._inflight.get(key)
                if ent is None:
                    self._retained.popleft()
                    continue
                if now - ent.retained_at < self.RETAIN_TTL_S:
                    break
                self._retained.popleft()
                self._inflight.pop(key, None)
                ent.evicted = True
                self._retained_bytes -= ent.size
                self.prefetch_waste_bytes += ent.size
                evicted.append(ent)
        for ent in evicted:
            if ent.seg is not None:
                ent.seg.close()

    def stats(self) -> dict:
        with self._lock:
            return {
                "deduped_pulls": self.deduped_pulls,
                "prefetch_hit_bytes": self.prefetch_hit_bytes,
                "prefetch_waste_bytes": self.prefetch_waste_bytes,
            }


def parse_segment_bytes(buf) -> Tuple[bytes, List[memoryview]]:
    """(payload_meta, buffer views) from raw segment bytes — the same
    layout Segment.raw_parts reads from an mmap (shm_store.py)."""
    view = memoryview(buf)
    magic, meta_len = _HEADER.unpack_from(view, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt segment stream")
    table = bytes(view[_HEADER.size:_HEADER.size + meta_len])
    offsets, lengths, payload = serialization.loads_inline(table)
    buffers = [view[o:o + n] for o, n in zip(offsets, lengths)]
    return payload, buffers
