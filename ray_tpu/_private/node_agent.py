"""Per-node agent process — the raylet-analog for non-head hosts.

The reference runs one raylet per node (``src/ray/raylet/main.cc:318``,
``node_manager.h:115``): it registers with the GCS, spawns language workers
on demand, and embeds the local object store.  This agent is the condensed
TPU-era equivalent:

- dials the head's TCP listener and registers its node (resources, labels,
  object-store id) — reference: ``NodeManager::RegisterGcs``;
- spawns worker processes when the head's scheduler leases one here
  (reference: ``worker_pool.cc``); workers dial the head directly, so the
  agent stays out of the task hot path;
- runs an OBJECT SERVER on its own TCP listener: consumers on other nodes
  (and the driver) pull segments directly as 1 MB chunk streams — the
  head brokers locations only (``ObjectManager::Push/Pull``,
  ``object_manager.h:117,206``; chunking per ``object_buffer_pool.h``);
- still serves head-relayed ``read_segment`` as the fallback path.

Run: ``python -m ray_tpu._private.node_agent`` with RAY_TPU_HEAD_ADDRESS /
RAY_TPU_AUTHKEY / RAY_TPU_AGENT_* env vars (see cluster_utils.Cluster).

Wire contract: the agent-plane verbs (``agent_ready``/``agent_ack``,
``spawn_worker``/``kill_worker``/``kill_worker_hard``,
``read_segment``/``segment``, ``unlink_segment``, ``oom_pressure``,
``worker_logs``, ``shutdown``, and the elastic-drain pair
``preempt_notice``/``drain_node`` — caps family ``drain_caps``,
advertised both ways) are declared in ``protocol.VERBS`` and
machine-checked against this module's send/handle sites by
``python -m ray_tpu.devtools.protocheck``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from multiprocessing.connection import Listener
from typing import Dict

from ray_tpu._private import object_transfer, protocol, recovery
from ray_tpu._private.shm_store import ShmStore


class _AgentStoreProxy:
    """Store view that always resolves the agent's CURRENT store — it is
    re-created with the session id after the head's ack, and the object
    server may accept consumers on both sides of that.  Reads attach;
    the only write path is the direct-put reservation (pushed values
    land here as public segments for this node's workers)."""

    def __init__(self, agent: "NodeAgent"):
        self._agent = agent

    def attach(self, name: str):
        return self._agent.store.attach(name)

    def reserve_put(self, oid_bin: bytes, total: int):
        return self._agent.store.reserve_put(oid_bin, total)


class NodeAgent:
    def __init__(self, head_address: str, authkey: bytes,
                 resources: Dict[str, float], shm_dir: str,
                 labels: Dict[str, str]):
        self.head_address = head_address
        self.authkey = authkey
        self.resources = resources
        self.labels = labels
        self.store_id = os.urandom(8).hex()
        self.shm_dir = shm_dir
        os.makedirs(shm_dir, exist_ok=True)
        # Attach-only store; re-created with the session id after the head
        # acks registration (the object server may get connections first).
        self.store = ShmStore(shm_dir=shm_dir)
        self.conn = None
        self.send_lock = threading.Lock()  # lock-order: io-guard
        self.workers: Dict[str, subprocess.Popen] = {}
        self.session = ""
        # Set once the head's agent_ack has been processed.  The memory
        # monitor gates on THIS, not on the config dict's truthiness — an
        # empty {} handshake payload must still arm the monitor (gating
        # on the dict left the thread spinning forever and the remote OOM
        # monitor silently disabled).
        self.head_config: Dict = {}
        self._handshake_done = threading.Event()
        self._stopped = False
        # Elastic drain state: a preemption notice (SIGTERM with
        # RAY_TPU_PREEMPT_SIGTERM=1, SIGUSR1, provider poll, chaos
        # "preempt") starts ONE self-drain; _drain_done releases it when
        # the head's drain_node ack lands (or the deadline expires and
        # the plug pulls).
        self._drain_lock = threading.Lock()
        self._draining = False
        self._drain_done = threading.Event()
        # Object server: direct chunked pulls from this node's store
        # (reference: the per-node object manager's transfer port).
        host = os.environ.get("RAY_TPU_AGENT_LISTEN_HOST", "127.0.0.1")
        self._obj_listener = Listener((host, 0), "AF_INET", backlog=64,
                                      authkey=authkey)
        # Advertise an address other hosts can reach: binding 0.0.0.0 (a
        # real multi-host cluster) must not advertise the bind address.
        adv = os.environ.get("RAY_TPU_AGENT_ADVERTISE_HOST")
        if adv is None:
            adv = host
            if adv == "0.0.0.0":
                import socket

                adv = socket.gethostbyname(socket.gethostname())
        port = self._obj_listener.address[1]
        self.object_addr = protocol.format_address((adv, port))
        threading.Thread(target=self._object_server, daemon=True,
                         name="agent-objsrv").start()
        threading.Thread(target=self._memory_monitor, daemon=True,
                         name="agent-memmon").start()
        threading.Thread(target=self._log_tailer, daemon=True,
                         name="agent-logmon").start()
        # Provider-poll preemption notice (the GCE metadata-server
        # analog): when RAY_TPU_PREEMPT_FILE names a path, its
        # appearance is the warning — self-drain starts the moment the
        # poller sees it.  Off (no thread) when unset.
        if os.environ.get("RAY_TPU_PREEMPT_FILE"):
            threading.Thread(target=self._preempt_poller, daemon=True,
                             name="agent-preempt-poll").start()
        # Heartbeat floor (failure detection): one ("heartbeat", ...)
        # per health_check_period_s so head-side silence from this node
        # is a SIGNAL, not an idle link.  The thread starts
        # unconditionally and gates per-tick on the handshake-resolved
        # knobs (env wins per node, else the head's agent_ack config).
        threading.Thread(target=self._heartbeat_loop, daemon=True,
                         name="agent-heartbeat").start()

    def _heartbeat_loop(self):
        while not self._stopped and not self._handshake_done.wait(0.2):
            pass
        period = float(self._failover_knob("RAY_TPU_HEALTH_CHECK_PERIOD_S",
                                           "health_check_period_s", 5.0))
        on = self._failover_knob("RAY_TPU_FAILURE_DETECTION",
                                 "failure_detection", True)
        if not on or period <= 0:
            return
        while not self._stopped:
            time.sleep(period)
            if self.conn is None:
                continue
            try:
                self._send(("heartbeat", self.store_id))
            except Exception:
                pass  # head blip: the serve loop owns reconnects

    def _preempt_poller(self):
        path = os.environ["RAY_TPU_PREEMPT_FILE"]
        while not self._stopped:
            if os.path.exists(path):
                self.notice_preemption("provider_poll")
                return
            time.sleep(0.25)

    def _log_tailer(self):
        """Ship this node's worker log lines to the head in 0.5s batches
        (the remote half of the driver's log_monitor)."""
        from ray_tpu._private.logtail import tail_worker_logs

        log_dir = os.path.join(self.shm_dir, "logs")
        offsets: Dict[str, int] = {}
        partial: Dict[str, bytes] = {}
        while not self._stopped:
            time.sleep(0.5)
            if self.conn is None:
                continue
            batch = tail_worker_logs(log_dir, offsets, partial)
            if batch:
                try:
                    self._send(("worker_logs", batch))
                except Exception:
                    pass

    def _memory_monitor(self):
        """Sample this node's memory; over threshold, report pressure to
        the head, which picks and kills a victim among OUR workers
        (reference: memory_monitor.h sampling in the raylet; the policy
        runs centrally here because the task table is head-resident).
        Knobs come from the head's agent_ack (so ``_system_config``
        applies cluster-wide), overridable per node via the standard
        ``RAY_TPU_MEMORY_MONITOR_*`` env flags (config.py)."""
        from ray_tpu._private import memmon
        from ray_tpu._private.config import Config

        env_cfg = Config.from_env()
        while not self._stopped and not self._handshake_done.wait(0.2):
            pass  # wait for the agent_ack (explicit handshake flag)
        head_cfg = self.head_config

        def knob(name):
            env_val = getattr(env_cfg, name)
            default = getattr(Config, name)
            return env_val if env_val != default else head_cfg.get(
                name, default)

        threshold = float(knob("memory_monitor_threshold"))
        interval = float(knob("memory_monitor_interval_s"))
        test_file = str(knob("memory_monitor_test_file"))
        if threshold <= 0:
            return
        while not self._stopped:
            time.sleep(interval)
            if self.conn is None:
                continue
            try:
                frac = memmon.memory_usage_fraction(test_file)
                if frac >= threshold:
                    self._send(("oom_pressure", frac))
            except Exception:
                pass

    def _send(self, msg):
        with self.send_lock:
            protocol.send(self.conn, msg)

    def _failover_knob(self, env_name: str, cfg_key: str, default):
        """Env wins when explicitly set (the per-node escape hatch);
        else the head-pushed agent_ack config (so the head's
        ``_system_config`` governs the whole cluster); else default."""
        raw = os.environ.get(env_name)
        if raw is not None:
            if isinstance(default, bool):
                return raw.lower() in ("1", "true", "yes")
            return type(default)(raw)
        return self.head_config.get(cfg_key, default)

    def connect(self, reconnect: bool = False):
        addr = protocol.parse_address(self.head_address)
        if reconnect:
            # Failover grace window: the head may take a while to
            # restart; keep dialing until it expires.
            grace = self._failover_knob("RAY_TPU_HEAD_RECONNECT_GRACE_S",
                                        "head_reconnect_grace_s", 20.0)
            deadline = time.time() + max(1.0, grace)
            attempt = 0
            while time.time() < deadline:
                try:
                    # Deadline-aware dial (connect timeout +
                    # SO_KEEPALIVE): a black-holed head fails this
                    # attempt in net_connect_timeout_s instead of
                    # eating the whole grace window in one kernel-
                    # default connect.
                    self.conn = protocol.dial(addr, authkey=self.authkey)
                    break
                except (ConnectionError, OSError):
                    attempt += 1
                    time.sleep(min(1.0, 0.1 * (attempt + 1)))
        else:
            for attempt in range(40):
                try:
                    self.conn = protocol.dial(addr, authkey=self.authkey)
                    break
                except (ConnectionError, OSError):
                    time.sleep(0.1 * (attempt + 1))
        if self.conn is None:
            raise SystemExit("node agent: cannot reach head at "
                             + self.head_address)
        prev_node = getattr(self, "node_id_hex", "")
        prev_session = self.session
        self._send(("agent_ready", {
            "resources": self.resources,
            "labels": self.labels,
            "store_id": self.store_id,
            "shm_dir": self.shm_dir,
            "object_addr": self.object_addr,
            # Advertised object-server verbs beyond the original
            # "fetch" — consumers only send e.g. "fetch_range" (striped
            # pulls) to peers that declare it, so an old agent that
            # would silently ignore the verb is never probed with it.
            "object_caps": list(object_transfer.CAPS),
            # Agent-plane verbs beyond the original set: the head sends
            # drain_node only to agents declaring it (old agents fall to
            # the legacy hard teardown), and probes suspicion suspects
            # only when they declared hc_probe.
            "agent_caps": ["drain_node", "preempt_notice", "hc_probe"],
            "pid": os.getpid(),
            "hostname": os.uname().nodename,
            # Failover re-registration: a restarted head re-binds this
            # node under its OLD id (matched by store_id) so surviving
            # workers' node identity stays valid.
            "reconnect": bool(reconnect),
            "node_id": prev_node,
            "session": prev_session,
        }))
        msg = protocol.recv(self.conn)
        assert msg[0] == "agent_ack", msg
        self.node_id_hex = msg[1]
        self.session = msg[2]
        # Head-pushed config this node mirrors (memory monitor knobs);
        # the event marks handshake completion even when the payload is
        # empty (see _memory_monitor).
        self.head_config = msg[3] if len(msg) > 3 else {}
        self._handshake_done.set()
        if reconnect and self.session == prev_session \
                and self.node_id_hex == prev_node:
            # Same session, same node: the restarted head restored our
            # registration — keep the live store (and its capacity
            # accounting) and the surviving workers exactly as they are.
            return
        if reconnect and self.workers:
            # The head came back as a DIFFERENT cluster (no restore):
            # our workers belong to a dead session — tear them down, as
            # the pre-failover reconnect always did.
            self._terminate_workers()
        # Store for read_segment + direct-put ingest.  Segments here are
        # otherwise created by this node's workers; the agent allocates
        # only put reservations — under the same NODE capacity the
        # workers get (shared flock'd counter), so pushed ingest cannot
        # overcommit tmpfs past what local puts respect, and an
        # over-capacity reservation degrades to this node's spill dir.
        self.store = ShmStore(shm_dir=self.shm_dir, session_id=self.session,
                              capacity=self._node_store_bytes())
        # Same node-local spill dir this node's workers resolve
        # (worker_main): the env override when set, else the per-session
        # default — so degraded put ingest lands where local spills do.
        self.store.spill_dir = os.environ.get(
            "RAY_TPU_SPILL_DIR_OVERRIDE",
            f"/tmp/ray_tpu_spill_{self.session}")

    def _object_server(self):
        object_transfer.accept_loop(self._obj_listener,
                                    _AgentStoreProxy(self),
                                    lambda: self._stopped,
                                    "agent-objconn")

    def serve(self):
        while not self._stopped:
            try:
                msg = protocol.recv(self.conn)
            except (EOFError, OSError):
                # Head gone.  If it persists GCS state it may restart on
                # the same port: keep our workers ALIVE (head_failover —
                # they park and re-register on their own conns) and
                # re-dial for a grace period before giving the node up
                # (reference: workers reconnecting across GCS restart,
                # gcs_failover_worker_reconnect_timeout,
                # ray_config_def.h:62).
                if not self._reconnect():
                    break
                continue
            tag = msg[0]
            # Chaos syncpoint: one firing per control message lets a
            # RAY_TPU_CHAOS "agent:agent_msg:N" rule take this node down
            # deterministically mid-protocol (no-op unless armed).
            recovery.syncpoint("agent_msg")
            if tag == "spawn_worker":
                self._spawn_worker(msg[1], msg[2])
            elif tag == "kill_worker":
                self._kill_worker(msg[1])
            elif tag == "kill_worker_hard":
                # SIGKILL, no graceful terminate: the chaos harness's
                # worker-crash injection (a terminate lets atexit/finally
                # blocks run, which is not what real crashes do).
                self._kill_worker(msg[1], hard=True)
            elif tag == "read_segment":
                threading.Thread(target=self._read_segment,
                                 args=(msg[1], msg[2]), daemon=True).start()
            elif tag == "unlink_segment":
                # Owner freed an object homed here (the owner-driven
                # deletion of local_object_manager.h:41).
                self.store.unlink(msg[1], msg[2])
            elif tag == "hc_probe":
                # Suspicion probe: answer from THIS reader thread
                # immediately — liveness of the LINK and the process,
                # independent of whatever the node's workers compute.
                try:
                    self._send(("heartbeat", self.store_id))
                except Exception:
                    pass
            elif tag == "drain_node":
                # The head drained this node (scale-down order, or the
                # ack to our own preempt_notice): release any waiting
                # self-drain and exit cleanly — workers terminated,
                # listeners closed, a zero-surprise departure.
                self._drain_done.set()
                break
            elif tag == "shutdown":
                break
        self.shutdown()

    def _reconnect(self) -> bool:
        if not self._failover_knob("RAY_TPU_AGENT_RECONNECT",
                                   "agent_reconnect", True):
            return False
        keep = self._failover_knob("RAY_TPU_HEAD_FAILOVER",
                                   "head_failover", True)
        if not keep:
            # Legacy reconnect: the old session's workers hold dead head
            # conns and stale state — terminate before re-dialing.  With
            # failover ON the workers stay ALIVE (they park and
            # re-register on their own conns; worker PIDs survive the
            # blip), and connect() tears them down only if the head
            # comes back as a different cluster.
            self._terminate_workers()
        try:
            self.conn.close()
        except Exception:
            pass
        self.conn = None  # connect()'s retry-exhaustion guard needs this
        try:
            self.connect(reconnect=keep)
            return True
        except (SystemExit, Exception):
            if keep:
                # Grace exhausted with workers still up: fall through to
                # shutdown(), which terminates them — the legacy outage.
                pass
            return False

    def notice_preemption(self, source: str):
        """Preemption-notice entry point (signal handlers, the provider
        poller, chaos ``preempt``): hand off to a thread — the drain
        blocks on the head, and signal context must not."""
        threading.Thread(target=self._self_drain, args=(source,),
                         daemon=True, name="agent-self-drain").start()

    def _self_drain(self, source: str):
        """Deadline-bounded self-drain before the plug pulls: ask the
        head to drain this node (``preempt_notice``), wait for its
        ``drain_node`` release, then exit.  Degrades to the legacy
        immediate exit when the drain protocol is off, the head never
        advertised the verbs, or the deadline expires — exactly the
        no-warning preemption the hard-kill recovery already covers."""
        with self._drain_lock:
            if self._draining or self._stopped:
                return
            self._draining = True
        # Chaos syncpoint: "agent:preempt:n" rules kill THIS process
        # mid-warning-window — the notice-then-plug-pulled-early drill.
        recovery.syncpoint("preempt")
        deadline_s = float(self._failover_knob("RAY_TPU_DRAIN_DEADLINE_S",
                                               "drain_deadline_s", 10.0))
        on = self._failover_knob("RAY_TPU_ELASTIC_DRAIN",
                                 "elastic_drain", True)
        head_drain_caps = tuple(self.head_config.get("drain_caps") or ())
        if on and self.conn is not None \
                and "preempt_notice" in head_drain_caps:
            try:
                self._send(("preempt_notice", deadline_s, source))
                self._drain_done.wait(deadline_s)
            except Exception:
                pass
        self.shutdown()
        os._exit(0)

    def _terminate_workers(self):
        """terminate -> wait -> kill, as in shutdown(): a TPU worker
        mid-computation takes seconds to die, and new workers must not
        race it for the chips."""
        for proc in self.workers.values():
            try:
                proc.terminate()
            except Exception:
                pass
        deadline = time.time() + 3.0
        for proc in self.workers.values():
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        self.workers.clear()

    def _node_store_bytes(self) -> int:
        """THIS node's store cap: the explicit env override, else 80% of
        the store filesystem (so an uncapped node can't fill tmpfs and
        die — per-node spilling engages instead).  Shared by worker
        spawns and the agent's own put-reservation admission."""
        if "RAY_TPU_STORE_BYTES" in os.environ:
            return int(os.environ["RAY_TPU_STORE_BYTES"] or 0)
        import shutil as _shutil

        try:
            return int(_shutil.disk_usage(self.shm_dir).total * 0.8)
        except OSError:
            return 0

    def _spawn_worker(self, worker_id_hex: str, env_overrides: Dict[str, str]):
        env = dict(os.environ)
        env.update(env_overrides)
        env["RAY_TPU_SHM_DIR_OVERRIDE"] = self.shm_dir
        env["RAY_TPU_STORE_ID"] = self.store_id
        # THIS node's store policy wins over head defaults (see
        # _node_store_bytes) — and matches the agent's own put-ingest
        # admission gate.  An explicit env value is forwarded VERBATIM
        # ("0" means uncapped and must reach the workers as such).
        if "RAY_TPU_STORE_BYTES" in os.environ:
            env["RAY_TPU_STORE_BYTES"] = os.environ["RAY_TPU_STORE_BYTES"]
        else:
            cap = self._node_store_bytes()
            if cap:
                env["RAY_TPU_STORE_BYTES"] = str(cap)
        if "RAY_TPU_POOL_BYTES" in os.environ:
            env["RAY_TPU_POOL_BYTES"] = os.environ["RAY_TPU_POOL_BYTES"]
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        existing = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = (pkg_root + (os.pathsep + existing
                                         if existing else ""))
        # Per-worker log file; the agent's tailer ships new lines to the
        # head (reference: per-node log_monitor shipping to the driver).
        log_dir = os.path.join(self.shm_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_f = open(os.path.join(log_dir, f"worker-{worker_id_hex}.log"),
                     "ab", buffering=0)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_main"],
            env=env, cwd=pkg_root, stdout=log_f,
            stderr=subprocess.STDOUT)
        log_f.close()
        self.workers[worker_id_hex] = proc

    def _kill_worker(self, worker_id_hex: str, hard: bool = False):
        proc = self.workers.pop(worker_id_hex, None)
        if proc is not None:
            try:
                proc.kill() if hard else proc.terminate()
            except Exception:
                pass

    def _read_segment(self, rid, name: str):
        try:
            seg = self.store.attach(name)
            meta, bufs = seg.raw_parts()
            # Copy out before close: the reply pickles them anyway.
            payload = (bytes(meta), [bytes(b) for b in bufs])
            seg.close()
            self._send(("segment", rid, True, payload))
        except Exception as e:  # noqa: BLE001
            self._send(("segment", rid, False, repr(e)))

    def shutdown(self):
        if self._stopped:
            return
        self._stopped = True
        self._terminate_workers()
        try:
            self.conn.close()
        except Exception:
            pass
        try:
            self._obj_listener.close()
        except Exception:
            pass


def main():
    # Opt-in chaos rules for agent processes (RAY_TPU_CHAOS,
    # "agent:<point>:<n>"); zero cost when unset.
    recovery.maybe_arm_env_chaos("agent")
    # Net-chaos rules (RAY_TPU_CHAOS_NET, "agent:<point>:<action>:<n>"):
    # gray failures — stalls/drops/delays at the protocol seam instead
    # of kills.  Imported lazily so an unarmed agent never loads the
    # harness.
    if os.environ.get("RAY_TPU_CHAOS_NET"):
        from ray_tpu import chaos as chaos_mod

        chaos_mod.maybe_arm_env_net_chaos("agent")
    agent = NodeAgent(
        head_address=os.environ["RAY_TPU_HEAD_ADDRESS"],
        authkey=bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"]),
        resources=json.loads(os.environ.get("RAY_TPU_AGENT_RESOURCES",
                                            '{"CPU": 1.0}')),
        shm_dir=os.environ.get("RAY_TPU_AGENT_SHM_DIR",
                               f"/tmp/ray_tpu_node_{os.getpid()}"),
        labels=json.loads(os.environ.get("RAY_TPU_AGENT_LABELS", "{}")),
    )
    # Preemption notice sources (elastic pods): SIGUSR1 is always a
    # notice (the chaos harness's graceful ``preempt`` and the
    # launcher's forwarded warning); SIGTERM becomes one only under
    # RAY_TPU_PREEMPT_SIGTERM=1 — what an operator sets on a real spot
    # VM, where SIGTERM IS the warning — because the test/teardown
    # path SIGTERMs agents for plain shutdown.
    signal.signal(signal.SIGUSR1,
                  lambda *_: agent.notice_preemption("sigusr1"))
    if os.environ.get("RAY_TPU_PREEMPT_SIGTERM", "").lower() in (
            "1", "true", "yes"):
        signal.signal(signal.SIGTERM,
                      lambda *_: agent.notice_preemption("sigterm"))
    else:
        signal.signal(signal.SIGTERM,
                      lambda *_: agent.shutdown() or sys.exit(0))
    agent.connect()
    agent.serve()


if __name__ == "__main__":
    main()
