"""Serialization with zero-copy buffer support.

TPU-native equivalent of the reference's serialization stack
(``python/ray/_private/serialization.py`` + the cloudpickle fork +
pickle5 out-of-band buffers for zero-copy numpy).  We use stock
``cloudpickle`` (baked into the image) with pickle protocol 5: large
contiguous buffers (numpy arrays, jax host arrays, bytes) are split out
of the pickle stream so they can be placed directly into shared memory
and mapped zero-copy by consumers — same trick plasma + pickle5 play in
the reference (``python/ray/includes/serialization.pxi``).

Layout of a serialized object:
    meta:    pickle-5 stream with out-of-band buffer references
    buffers: list of contiguous memoryviews, 64-byte aligned when placed
             into a shm segment (TPU DMA + numpy both like alignment).
"""

from __future__ import annotations

import pickle
from typing import Any, List, Tuple

import cloudpickle

ALIGNMENT = 64


def loads(meta: bytes, buffers: List[memoryview]) -> Any:
    return pickle.loads(meta, buffers=buffers)


def dumps_adaptive(value: Any, max_inline: int):
    """One serialization pass deciding inline vs out-of-band placement.

    Returns ``("inline", data)`` for values whose serialized form fits
    ``max_inline`` (data is a self-contained in-band pickle stream), else
    ``("parts", meta, buffer_views, total_size)`` for the shm path where
    each buffer is memcpy'd exactly once into the segment.

    When no out-of-band buffers were captured, ``meta`` is already a
    complete loadable stream — no second pickle pass.
    """
    buffers: List[pickle.PickleBuffer] = []
    meta = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    if not buffers:
        if len(meta) <= max_inline:
            return ("inline", meta)
        return ("parts", meta, [], len(meta))
    views = []
    for b in buffers:
        raw = b.raw()
        if not raw.contiguous:
            raw = memoryview(bytes(raw))
        views.append(raw.cast("B"))
    total = len(meta) + sum(len(v) for v in views)
    if total <= max_inline:
        # Small-but-buffered (e.g. a tiny ndarray): re-pickle in-band.
        return ("inline", cloudpickle.dumps(value, protocol=5))
    return ("parts", meta, views, total)


def dumps_inline(value: Any) -> bytes:
    """Single-buffer serialization for small objects carried inside protocol
    messages (reference: inline objects below max_direct_call_object_size,
    src/ray/common/ray_config_def.h:212)."""
    return cloudpickle.dumps(value, protocol=5)


def loads_inline(data: bytes) -> Any:
    return pickle.loads(data)


def aligned_offsets(sizes: List[int], base: int = 0) -> Tuple[List[int], int]:
    """Compute ALIGNMENT-aligned offsets for buffers packed in one segment.

    Returns (offsets, total_size)."""
    offsets = []
    cur = base
    for s in sizes:
        cur = (cur + ALIGNMENT - 1) // ALIGNMENT * ALIGNMENT
        offsets.append(cur)
        cur += s
    return offsets, cur
