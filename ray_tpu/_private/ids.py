"""Binary IDs for tasks, objects, actors, and nodes.

TPU-native re-design of the reference's ID model (reference:
``src/ray/common/id.h:58,127,175,261,333`` — BaseID/TaskID/ObjectID/ActorID/
PlacementGroupID).  The reference packs lineage into the ID bytes (an ObjectID
embeds its generating TaskID plus a return index).  We keep that property —
it gives free owner routing and makes IDs self-describing — but use a smaller
16-byte layout since we do not need Ray's legacy 28-byte compatibility.
"""

from __future__ import annotations

import os
import threading
import binascii

_ID_SIZE = 16

# ObjectID = 12-byte task prefix + 4-byte little-endian index.
_TASK_PREFIX_SIZE = 12
_INDEX_SIZE = 4


class BaseID:
    """Immutable binary identifier (reference: src/ray/common/id.h:58)."""

    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != _ID_SIZE:
            raise ValueError(
                f"{type(self).__name__} must be {_ID_SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)
        self._hash = hash(self._bytes)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(_ID_SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(binascii.unhexlify(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * _ID_SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class UniqueID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class JobID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class TaskID(BaseID):
    """Task identifier; its first 12 bytes prefix the ObjectIDs it returns
    (reference: src/ray/common/id.h:175 — ObjectID embeds owner TaskID)."""

    def object_id(self, index: int) -> "ObjectID":
        return ObjectID(
            self._bytes[:_TASK_PREFIX_SIZE] + index.to_bytes(_INDEX_SIZE, "little")
        )


class ObjectID(BaseID):
    """Object identifier = task prefix + return index
    (reference: src/ray/common/id.h:261)."""

    def task_prefix(self) -> bytes:
        return self._bytes[:_TASK_PREFIX_SIZE]

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[_TASK_PREFIX_SIZE:], "little")

    @classmethod
    def for_put(cls) -> "ObjectID":
        # Puts share the seed+counter prefix space with index 0xFFFFFFFF
        # to distinguish from task returns (whose index is a small int).
        n = _task_counter.next()
        return cls(_PROC_SEED + (n & 0xFFFFFFFF).to_bytes(4, "little")
                   + b"\xff\xff\xff\xff")

    def is_put(self) -> bool:
        return self._bytes[_TASK_PREFIX_SIZE:] == b"\xff\xff\xff\xff"


class _Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def next(self) -> int:
        with self._lock:
            self._n += 1
            return self._n


_task_counter = _Counter()

# One entropy draw per process; ids are seed + counter (reference: task
# ids are deterministic child ids, id.h:175 — and os.urandom per id was
# ~40us, a measurable slice of the per-task submit budget).
_PROC_SEED = os.urandom(_TASK_PREFIX_SIZE - 4)


def new_task_id() -> TaskID:
    """Process-unique task ID: 8-byte process seed + 4-byte counter
    prefix (collision across processes needs a seed collision)."""
    n = _task_counter.next()
    return TaskID(_PROC_SEED + (n & 0xFFFFFFFF).to_bytes(4, "little")
                  + b"\x00\x00\x00\x00")
