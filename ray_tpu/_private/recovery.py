"""Fault-tolerance plumbing shared by the head and workers.

Three pieces, kept dependency-free so every process tier can import it
(driver runtime, worker runtime, node agent, the chaos harness):

- ``LineageTable`` — a BOUNDED object -> producing-TaskSpec table
  (reference: lineage pinning in ``task_manager.h:174`` + the recovery
  walk of ``object_recovery_manager.h:41``).  The owner records each
  submitted spec; a lost object is rebuilt by re-executing its producer.
  Entries evict when the last return object's refcount drops OR when the
  table's byte budget (``config.lineage_bytes_budget``) overflows —
  mirroring the reference's ``lineage_pinning`` byte cap, so lineage is
  metadata the owner already holds, never an unbounded log.

- retry classification — ``retry_matches``.  ``max_retries`` budgets
  SYSTEM failures (worker/node death, OOM kills — classified at their
  discovery sites in the death paths); application exceptions are
  retried only under the explicit ``retry_exceptions=`` opt-in
  (reference: ``retry_exceptions`` on ``@ray.remote``).

- chaos syncpoints — ``syncpoint(name)`` is a near-zero-cost hook
  (one module-global ``is None`` check on the fast path) that the
  chaos harness (``ray_tpu.chaos``) arms in-process, and that
  ``RAY_TPU_CHAOS`` env rules arm in spawned workers/agents for
  deterministic mid-operation kills.  Never active unless explicitly
  opted in.  Points: dispatch/result/lease_grant (head), exec_start
  (worker), pull_chunk (every transfer chunk), agent_msg (agent
  control messages), snapshot/dispatch (standalone head), and
  ``preempt`` — fired at the start of an agent's self-drain, so an
  ``agent:preempt:1`` rule models the warning window getting yanked
  mid-drain (notice received, plug pulled early).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

# --------------------------------------------------------------- lineage --

def seg_oid_hex(name: str) -> Optional[str]:
    """Object id hex embedded in a segment name or spill path
    (``rtpu-<session>-<oid hex>``, shm_store.py) — THE one
    implementation of that naming rule for loss errors and owned-object
    recovery; returns None for anything unparseable."""
    try:
        tail = os.path.basename(name).rsplit("-", 1)[1]
        bytes.fromhex(tail)
        return tail
    except Exception:
        return None


_SPEC_BASE_COST = 512       # table entry + ids + small spec fields
_DESCR_COST = 64            # non-inline arg descriptor (name + ints)


def spec_cost(spec: dict) -> int:
    """Cheap byte-cost estimate for retaining one TaskSpec: inline arg
    payloads dominate; everything else is near-constant metadata.  Must
    stay O(#args) with no serialization — lineage recording sits on the
    submit hot path and its steady-state overhead must be ~zero."""
    cost = _SPEC_BASE_COST
    for a in spec.get("args", ()):
        cost += (len(a[1]) if a and a[0] == "inline" else _DESCR_COST)
    for a in (spec.get("kwargs") or {}).values():
        cost += (len(a[1]) if a and a[0] == "inline" else _DESCR_COST)
    return cost


class LineageTable:
    """Bounded lineage: task prefix (12 bytes) -> entry dict.

    An entry holds the producing ``spec``, the set of its still-alive
    return-object bins, the remaining reconstruction budget (``retries``,
    seeded from the spec's ``max_retries`` — reconstruction is a SYSTEM-
    failure retry and draws from the same budget), and its byte ``cost``.

    LOCK ORDER: ``_lock`` is an independent LEAF — no other lock is ever
    acquired while holding it, and callers (the head's runtime lock, the
    DirectCaller ownership lock) may hold their own lock when calling in.
    Pinned in tests/test_lockcheck.py.  Eviction never runs callbacks
    under ``_lock``: evicted entries are RETURNED for the caller to
    release resources at its own locking level.
    """

    def __init__(self, budget_bytes: int):
        self._lock = threading.Lock()  # lock-order: leaf
        self.budget = int(budget_bytes)
        self._entries: Dict[bytes, dict] = {}
        self._order: deque = deque()  # FIFO of task prefixes for eviction
        self.bytes = 0
        self.evicted = 0

    def record(self, spec: dict,
               default_retries: int = 3) -> List[dict]:
        """Retain ``spec``; returns the entries evicted to stay within
        the byte budget (oldest-first) so the caller can release any
        resources it pinned for them."""
        from ray_tpu._private.ids import TaskID

        prefix = spec["task_id"][:12]
        tid = TaskID(spec["task_id"])
        cost = spec_cost(spec)
        entry = {
            "spec": spec,
            "alive": {tid.object_id(i).binary()
                      for i in range(spec["num_returns"])},
            "retries": spec.get("max_retries", default_retries),
            "cost": cost,
        }
        evicted: List[dict] = []
        with self._lock:
            prev = self._entries.get(prefix)
            if prev is not None:
                self.bytes -= prev["cost"]
            self._entries[prefix] = entry
            if prev is None:
                self._order.append(prefix)
            self.bytes += cost
            while self.bytes > self.budget > 0 and len(self._entries) > 1:
                old_prefix = self._order.popleft()
                if old_prefix == prefix:
                    self._order.append(prefix)
                    continue
                old = self._entries.pop(old_prefix, None)
                if old is None:
                    continue
                self.bytes -= old["cost"]
                self.evicted += 1
                evicted.append(old)
        return evicted

    def get(self, prefix: bytes) -> Optional[dict]:
        with self._lock:
            return self._entries.get(prefix)

    def __contains__(self, prefix: bytes) -> bool:
        with self._lock:
            return prefix in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def note_attempt(self, prefix: bytes) -> bool:
        """Consume one reconstruction attempt; False when depleted (the
        caller then refuses recovery — depleted retries surface as
        ``ObjectLostError``)."""
        with self._lock:
            entry = self._entries.get(prefix)
            if entry is None or entry["retries"] <= 0:
                return False
            entry["retries"] -= 1
            return True

    def release(self, oid_bin: bytes) -> Optional[dict]:
        """A return object's refcount dropped; when the entry's last one
        goes, the entry is dropped and returned (caller releases the
        spec's pinned resources)."""
        prefix = oid_bin[:12]
        with self._lock:
            entry = self._entries.get(prefix)
            if entry is None:
                return None
            entry["alive"].discard(oid_bin)
            if entry["alive"]:
                return None
            self._entries.pop(prefix, None)
            # The prefix stays in _order as a TOMBSTONE (eviction skips
            # entries no longer present) — a deque.remove here would be
            # O(table) under the owner's big lock on every object free.
            # Compact when tombstones dominate, amortizing to O(1).
            self.bytes -= entry["cost"]
            if len(self._order) > 4 * len(self._entries) + 64:
                self._order = deque(p for p in self._order
                                    if p in self._entries)
            return entry

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self.bytes,
                    "evicted": self.evicted}


# --------------------------------------------- retry classification --
# System failures (worker/node death, OOM kills) are classified AT
# their discovery sites — the death paths in runtime.py/direct.py
# decrement retries_left directly; only the app-error opt-in needs a
# shared matcher.

def retry_matches(retry_exceptions, err: BaseException) -> bool:
    """Whether an APPLICATION error qualifies for the opt-in retry.
    ``retry_exceptions`` is ``True`` (any app error) or a list/tuple of
    exception types matched against the task error's original cause."""
    if not retry_exceptions:
        return False
    from ray_tpu import exceptions as exc

    if not isinstance(err, exc.TaskError):
        return False  # system failures ride the max_retries path instead
    if retry_exceptions is True:
        return True
    cause = getattr(err, "cause", None)
    try:
        types = tuple(t for t in retry_exceptions
                      if isinstance(t, type) and issubclass(t, BaseException))
    except TypeError:
        return False
    return cause is not None and isinstance(cause, types)


# -------------------------------------------------- chaos syncpoints --

# The armed hook: callable(name, info_dict) or None.  The fast path is
# one global read + None check; nothing else runs until a controller
# (ray_tpu.chaos.ChaosController) or an env rule arms it.
_CHAOS_HOOK = None


def set_chaos_hook(fn) -> None:
    global _CHAOS_HOOK
    _CHAOS_HOOK = fn


def chaos_armed() -> bool:
    return _CHAOS_HOOK is not None


def syncpoint(name: str, **info) -> None:
    """Named chaos syncpoint.  ~Zero cost unless a controller armed the
    process (opt-in via ``RAY_TPU_CHAOS`` or an explicit
    ``ChaosController``)."""
    hook = _CHAOS_HOOK
    if hook is not None:
        hook(name, info)


def parse_chaos_rules(raw: str) -> List[Tuple[str, str, int]]:
    """``RAY_TPU_CHAOS`` grammar: comma-separated ``role:point:n`` rules
    — processes of ``role`` ("worker" / "agent" / "driver") exit hard at
    the ``n``-th firing of syncpoint ``point``.  Unparseable rules are
    ignored (chaos must never break a production boot that inherited a
    stray env var)."""
    rules = []
    for part in (raw or "").split(","):
        bits = part.strip().split(":")
        if len(bits) != 3:
            continue
        role, point, n = bits
        try:
            rules.append((role, point, max(1, int(n))))
        except ValueError:
            continue
    return rules


def maybe_arm_env_chaos(role: str) -> bool:
    """Arm env-driven chaos rules for this process (worker/agent entry
    points call this).  Each rule fires AT MOST ONCE per cluster: the
    first process to reach the rule's count claims an O_EXCL lockfile
    keyed by (session, rule) and dies with ``os._exit(137)`` — a hard
    kill indistinguishable from a crash, which is the point.  Without
    the claim the process sails through, so a RETRIED task does not die
    again at the same spot and the cluster converges."""
    rules = [r for r in parse_chaos_rules(os.environ.get("RAY_TPU_CHAOS", ""))
             if r[0] == role]
    if not rules:
        return False
    session = os.environ.get("RAY_TPU_SESSION", "nosession")
    counters: Dict[str, int] = {}
    counters_lock = threading.Lock()

    def hook(name, _info):
        for r_role, point, n in rules:
            if point != name:
                continue
            with counters_lock:
                counters[point] = counters.get(point, 0) + 1
                hit = counters[point] >= n
            if not hit:
                continue
            claim = os.path.join(
                os.environ.get("RAY_TPU_CHAOS_DIR", "/tmp"),
                f"ray_tpu_chaos_{session}_{r_role}_{point}_{n}")
            try:
                fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except OSError:
                continue  # another process already died for this rule
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
            os._exit(137)

    set_chaos_hook(hook)
    return True
