"""Pip runtime-env materialization: venv per requirements hash.

Reference: ``python/ray/_private/runtime_env/pip.py`` — a task/actor with
``runtime_env={"pip": [...]}`` runs in a virtualenv holding exactly those
packages, built once per unique requirements list and cached.

TPU-era shape: the WORKER builds (or reuses) the venv at startup and
re-execs itself under the venv's interpreter (``--system-site-packages``
keeps jax/numpy/cloudpickle importable).  Building in the worker keeps the
head's dispatch loop out of multi-second pip installs — the reference puts
this in its per-node agent for the same reason.  Concurrent workers of the
same env serialize on an flock so the build runs once.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
from typing import Any, Dict, List, Tuple

DEFAULT_BASE = "/tmp/ray_tpu_venvs"


def normalize_pip_spec(pip: Any) -> Tuple[List[str], List[str]]:
    """User spec -> (packages, extra pip options).  Accepts the reference
    forms: a list of requirement strings or {"packages": [...],
    "pip_install_options": [...]}."""
    if isinstance(pip, (list, tuple)):
        return [str(p) for p in pip], []
    if isinstance(pip, dict):
        return ([str(p) for p in pip.get("packages", [])],
                [str(o) for o in pip.get("pip_install_options", [])])
    raise ValueError(f"bad pip runtime_env spec: {pip!r}")


def pip_env_hash(pip: Any) -> str:
    packages, options = normalize_pip_spec(pip)
    blob = json.dumps([packages, options]).encode()
    return hashlib.sha1(blob).hexdigest()[:16]


def ensure_pip_env(pip: Any, base_dir: str = DEFAULT_BASE) -> str:
    """Build-or-reuse the venv for ``pip``; returns its python binary.
    Raises RuntimeError (with pip's output) on build failure."""
    import fcntl
    import venv

    packages, options = normalize_pip_spec(pip)
    key = pip_env_hash(pip)
    target = os.path.join(base_dir, key)
    python = os.path.join(target, "bin", "python")
    marker = os.path.join(target, ".ray_tpu_ok")
    if os.path.exists(marker):
        return python
    os.makedirs(base_dir, exist_ok=True)
    lock_path = os.path.join(base_dir, f".{key}.lock")
    with open(lock_path, "w", encoding="utf-8") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):  # raced: another worker built it
                return python
            venv.create(target, system_site_packages=True, with_pip=True,
                        clear=True)
            if packages:
                cmd = [python, "-m", "pip", "install",
                       "--disable-pip-version-check"]
                cmd += options + packages
                out = subprocess.run(cmd, capture_output=True, text=True,
                                     timeout=600)
                if out.returncode != 0:
                    raise RuntimeError(
                        f"pip install failed for {packages}: "
                        f"{out.stderr[-2000:]}")
            with open(marker, "w", encoding="utf-8") as f:
                f.write(json.dumps({"packages": packages,
                                    "options": options}))
            return python
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def maybe_reexec_into_pip_env():
    """Worker-startup hook: with RAY_TPU_PIP_SPEC set and not yet inside
    the target venv, build it and exec this process under its
    interpreter (env preserved; the reference instead launches workers
    through the agent with the materialized env's python)."""
    spec_json = os.environ.get("RAY_TPU_PIP_SPEC")
    if not spec_json:
        return
    spec = json.loads(spec_json)
    key = pip_env_hash(spec)
    if os.environ.get("RAY_TPU_PIP_ACTIVE") == key:
        return  # already re-exec'd
    try:
        python = ensure_pip_env(spec)
    except Exception as e:  # noqa: BLE001 — startup failure is terminal
        print(f"[ray_tpu worker {os.getpid()}] runtime_env pip setup "
              f"failed: {e}", file=sys.stderr)
        raise SystemExit(1)
    env = dict(os.environ, RAY_TPU_PIP_ACTIVE=key)
    os.execve(python,
              [python, "-m", "ray_tpu._private.worker_main"], env)
