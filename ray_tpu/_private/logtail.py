"""Incremental worker-log tailing, shared by the head's log monitor and
every node agent's shipper (reference: log_monitor.py file tailing)."""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

# A "line" that never sees a newline (carriage-return progress bars)
# flushes once it exceeds this, so tqdm-style output cannot grow the
# partial buffer without bound (and still reaches the driver).
_PARTIAL_FLUSH_AT = 64 << 10
_READ_CAP = 1 << 20


def tail_worker_logs(log_dir: str, offsets: Dict[str, int],
                     partial: Dict[str, bytes]
                     ) -> List[Tuple[str, List[str]]]:
    """One tail pass over ``log_dir``'s worker-*.log files.  ``offsets``
    and ``partial`` are caller-owned state carried between passes;
    returns [(worker_id_hex, new_lines), ...]."""
    out: List[Tuple[str, List[str]]] = []
    try:
        names = os.listdir(log_dir)
    except OSError:
        return out
    for name in names:
        if not name.startswith("worker-") or not name.endswith(".log"):
            continue
        path = os.path.join(log_dir, name)
        try:
            size = os.path.getsize(path)
            off = offsets.get(name, 0)
            if size <= off:
                continue
            with open(path, "rb") as f:
                f.seek(off)
                chunk = partial.pop(name, b"") + f.read(
                    min(size - off, _READ_CAP))
            offsets[name] = off + min(size - off, _READ_CAP)
        except OSError:
            continue
        *lines, rest = chunk.split(b"\n")
        if len(rest) > _PARTIAL_FLUSH_AT:
            # \r-rewriting output: ship the most recent screenful rather
            # than buffering the stream forever.
            lines.append(rest.split(b"\r")[-1])
            rest = b""
        if rest:
            partial[name] = rest
        if lines:
            out.append((name[len("worker-"):-len(".log")],
                        [ln.decode("utf-8", "replace") for ln in lines]))
    return out
