"""Driver ⇄ worker wire protocol.

The reference speaks gRPC between core workers and raylets
(``src/ray/rpc/``, ``core_worker.proto``, ``node_manager.proto``).  Our v1
topology is one driver process + N worker processes per host, so the
transport is a duplex OS pipe per worker (``multiprocessing.Pipe``) carrying
pickled tuples — no serialization schema to keep in sync, and small-message
latency (~10µs) far below gRPC's.  A TCP transport with the same message set
slots in for multi-host (see node.py).

Message grammar (first element = type tag):

driver → worker
  ("exec",   task: dict)            run a task / actor method
  ("create_actor", spec: dict)      instantiate actor class on this worker
  ("func",   func_id, payload)      function/class definition (cloudpickle)
  ("obj",    req_id, ok, descr)     reply to a worker "get"
  ("submitted", req_id)             ack of a nested "submit"
  ("kill",   )                      graceful shutdown
worker → driver
  ("ready",  worker_id_hex, pid)
  ("result", task_id_bytes, ok, returns: list[Descr], meta: dict)
  ("get",    req_id, object_id_bytes, timeout)
  ("need_func", func_id, task: dict)  exec bounced: definition not cached
  ("submit", spec: dict)            nested task submission
  ("put",    object_id_bytes, descr)
  ("addref", object_id_bytes) / ("decref", object_id_bytes)
  ("blocked", task_id_bytes) / ("unblocked", task_id_bytes)
  ("actor_exit", actor_id_bytes, ok, error_descr)

Object descriptors (Descr) carry values between processes:
  ("inline", bytes)                 pickled value, small
  ("shm", name, size)               shared-memory segment (zero-copy mmap)
  ("error", bytes)                  pickled exception
"""

from __future__ import annotations

import pickle


def send(conn, msg: tuple):
    conn.send_bytes(pickle.dumps(msg, protocol=5))


def recv(conn) -> tuple:
    return pickle.loads(conn.recv_bytes())


INLINE = "inline"
SHM = "shm"
ERROR = "error"
