"""Driver ⇄ worker wire protocol.

The reference speaks gRPC between core workers and raylets
(``src/ray/rpc/``, ``core_worker.proto``, ``node_manager.proto``).  Our v1
topology is one driver process + N worker processes per host, so the
transport is a duplex OS pipe per worker (``multiprocessing.Pipe``) carrying
pickled tuples — no serialization schema to keep in sync, and small-message
latency (~10µs) far below gRPC's.  A TCP transport with the same message set
slots in for multi-host (see node.py).

Message grammar (first element = type tag):

driver → worker
  ("exec",   task: dict)            run a task / actor method
  ("create_actor", spec: dict)      instantiate actor class on this worker
  ("func",   func_id, payload)      function/class definition (cloudpickle)
  ("obj",    req_id, ok, descr)     reply to a worker "getparts"
  ("mgot",   req_id, [(ok, descr)]) reply to a batched "mget"
  ("free_segment", name, size, reusable)  owner freed a segment this worker
                                    created; pool pages iff reusable
  ("kill",   )                      graceful shutdown
worker → driver
  ("ready",  worker_id_hex, pid)
  ("result", task_id_bytes, ok, returns: list[Descr], meta: dict)
  ("mget",   req_id, [object_id_bytes], timeout)   batched get
  ("submit", 0, spec: dict)         nested task submission (fire-and-forget;
                                    per-conn FIFO makes later uses safe)
  ("put",    object_id_bytes, descr, nested_ids)
  ("put_parts", object_id_bytes, meta, [buffers], nested_ids)
                                    legacy client put: whole value in one
                                    control message, head assembles
  ("put_commit", object_id_bytes, descr, nested_ids)
                                    direct put: the payload already
                                    streamed into the destination store
                                    over the object-server data plane
                                    (reserve_put/put_range/commit_put/
                                    abort_put verbs, capability-gated);
                                    the control plane sees only this
                                    O(1) descriptor registration
  ("addref", object_id_bytes) / ("decref", object_id_bytes)
  ("decref_batch", [object_id_bytes])   buffered ref drops
  ("blocked", task_id_bytes) / ("unblocked", task_id_bytes)
  ("actor_exit", actor_id_bytes, ok, error_descr)
lease plane (decentralized dispatch; all verbs are capability-gated:
holders opt in via the ``lease_req`` opts dict / the ``_spill_ok`` task
flag, so a peer that never advertises them is never sent one)
  ("lease_req", rid, resources, n[, opts])   worker/client asks for leases;
                                    opts {"v": 1, "hint": node_hex} selects
                                    the dict-shaped reply {"grants":
                                    [(wid, addr, node_hex)...], "slots",
                                    "ttl", "hint"} (bare list without)
  ("lease_grant", klass_items, grants, slots, ttl, hint)   head → holder:
                                    unsolicited bulk grant piggybacked on a
                                    head-brokered submit burst
  ("lease_renew", [wid_hex])        holder liveness, one message per N
                                    leased pushes (lease_renew_tasks)
  ("lease_revoke", [wid_hex])       head → holder: leased worker gone
                                    (node death / TTL expiry); rides the
                                    conflation sender
  ("dspill", rid, info)             executor → holder on the direct conn:
                                    pushed task bounced (queue over
                                    lease_spillback_depth); info names the
                                    bouncing executor's node — the
                                    next-best hint rides the lease grant
either direction
  ("batch",  [msg, ...])            envelope: N back-to-back messages as
                                    ONE pickle + one write.  Receivers
                                    unwrap and handle each message in
                                    order; sub-messages are never
                                    themselves batches.  Purely an
                                    optimization: a peer that only ever
                                    sends unbatched messages (or the
                                    legacy "msg_batch" form) interoperates
                                    unchanged (reference: gRPC stream
                                    write coalescing in
                                    direct_task_transport.cc).

Object descriptors (Descr) carry values between processes:
  ("inline", bytes)                 pickled value, small
  ("shm", name, size, store_id)     shared-memory segment (zero-copy mmap,
                                    attachable only by processes sharing the
                                    creating host's object store)
  ("parts", meta, [bytes...])       serialized parts shipped over the wire —
                                    the cross-node transfer form (reference:
                                    object_manager.h:206 chunked push/pull)
  ("error", bytes)                  pickled exception

Transport: same message set over an AF_UNIX socket (workers on the head
host) or TCP (node agents and the workers they spawn on other hosts) —
the reference speaks gRPC for both (``node_manager.proto``).
"""

from __future__ import annotations

import os
import pickle


def enable_nodelay(conn) -> None:
    """Disable Nagle on a TCP connection (no-op for AF_UNIX pipes).

    The protocol often issues back-to-back small sends on one socket
    (blocked + mget, decref_batch + submit); with Nagle on, the second
    write stalls until the peer's delayed ACK (~40ms) — the classic
    Nagle/delayed-ACK interaction that collapsed client-mode gets to
    ~26/s.  The reference's gRPC channels disable Nagle the same way."""
    import socket as _socket

    try:
        fd = os.dup(conn.fileno())
    except (OSError, AttributeError):
        return
    try:
        s = _socket.socket(fileno=fd)
    except OSError:
        os.close(fd)
        return
    try:
        # Options bind to the open file description, which the original
        # connection shares with this dup.
        s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    except OSError:
        pass  # AF_UNIX
    finally:
        s.close()


def send(conn, msg: tuple):
    conn.send_bytes(pickle.dumps(msg, protocol=5))


def recv(conn) -> tuple:
    return pickle.loads(conn.recv_bytes())


# Batch-envelope tag (plus the pre-envelope spelling still emitted by old
# peers; both unwrap identically).
BATCH = "batch"
LEGACY_BATCH = "msg_batch"


def make_batch(msgs):
    """List of messages -> the cheapest single wire message: the message
    itself for a singleton, a ("batch", msgs) envelope otherwise."""
    if len(msgs) == 1:
        return msgs[0]
    return (BATCH, msgs)


def send_batch(conn, msgs) -> None:
    """Ship back-to-back messages as ONE pickle + one write (no-op for an
    empty list) — the wire-level amortization that keeps fan-out paths at
    ~O(n/batch) syscalls instead of O(n)."""
    if not msgs:
        return
    send(conn, make_batch(msgs))


def is_batch(msg) -> bool:
    return msg[0] == BATCH or msg[0] == LEGACY_BATCH


INLINE = "inline"
SHM = "shm"
PARTS = "parts"
SPILLED = "spilled"  # ("spilled", path, size, store_id): on-disk segment
ERROR = "error"


def format_address(addr) -> str:
    """Listener address -> env-var string ("tcp://host:port" or a path)."""
    if isinstance(addr, tuple):
        return f"tcp://{addr[0]}:{addr[1]}"
    return addr


def parse_address(s: str):
    """Env-var string -> Client()-compatible address."""
    if s.startswith("tcp://"):
        host, port = s[len("tcp://"):].rsplit(":", 1)
        return (host, int(port))
    return s
