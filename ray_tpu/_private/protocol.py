"""Driver ⇄ worker wire protocol.

The reference speaks gRPC between core workers and raylets
(``src/ray/rpc/``, ``core_worker.proto``, ``node_manager.proto``).  Our v1
topology is one driver process + N worker processes per host, so the
transport is a duplex OS pipe per worker (``multiprocessing.Pipe``) carrying
pickled tuples — no serialization schema to keep in sync, and small-message
latency (~10µs) far below gRPC's.  A TCP transport with the same message set
slots in for multi-host (see node.py).

Message grammar (first element = type tag):

driver → worker
  ("exec",   task: dict)            run a task / actor method
  ("create_actor", spec: dict)      instantiate actor class on this worker
  ("func",   func_id, payload)      function/class definition (cloudpickle)
  ("obj",    req_id, ok, descr)     reply to a worker "getparts"
  ("mgot",   req_id, [(ok, descr)]) reply to a batched "mget"
  ("free_segment", name, size, reusable)  owner freed a segment this worker
                                    created; pool pages iff reusable
  ("kill",   )                      graceful shutdown
worker → driver
  ("ready",  worker_id_hex, pid)
  ("result", task_id_bytes, ok, returns: list[Descr], meta: dict)
  ("mget",   req_id, [object_id_bytes], timeout)   batched get
  ("submit", 0, spec: dict)         nested task submission (fire-and-forget;
                                    per-conn FIFO makes later uses safe)
  ("put",    object_id_bytes, descr, nested_ids)
  ("put_parts", object_id_bytes, meta, [buffers], nested_ids)
                                    legacy client put: whole value in one
                                    control message, head assembles
  ("put_commit", object_id_bytes, descr, nested_ids)
                                    direct put: the payload already
                                    streamed into the destination store
                                    over the object-server data plane
                                    (reserve_put/put_range/commit_put/
                                    abort_put verbs, capability-gated);
                                    the control plane sees only this
                                    O(1) descriptor registration
  ("addref", object_id_bytes) / ("decref", object_id_bytes)
  ("decref_batch", [object_id_bytes])   buffered ref drops
  ("blocked", task_id_bytes) / ("unblocked", task_id_bytes)
lease plane (decentralized dispatch; all verbs are capability-gated:
holders opt in via the ``lease_req`` opts dict / the ``_spill_ok`` task
flag, so a peer that never advertises them is never sent one)
  ("lease_req", rid, resources, n[, opts])   worker/client asks for leases;
                                    opts {"v": 1, "hint": node_hex} selects
                                    the dict-shaped reply {"grants":
                                    [(wid, addr, node_hex)...], "slots",
                                    "ttl", "hint"} (bare list without)
  ("lease_grant", klass_items, grants, slots, ttl, hint)   head → holder:
                                    unsolicited bulk grant piggybacked on a
                                    head-brokered submit burst
  ("lease_renew", [wid_hex])        holder liveness, one message per N
                                    leased pushes (lease_renew_tasks)
  ("lease_revoke", [wid_hex])       head → holder: leased worker gone
                                    (node death / TTL expiry); rides the
                                    conflation sender
  ("dspill", rid, info)             executor → holder on the direct conn:
                                    pushed task bounced (queue over
                                    lease_spillback_depth); info names the
                                    bouncing executor's node — the
                                    next-best hint rides the lease grant
either direction
  ("batch",  [msg, ...])            envelope: N back-to-back messages as
                                    ONE pickle + one write.  Receivers
                                    unwrap and handle each message in
                                    order; sub-messages are never
                                    themselves batches.  Purely an
                                    optimization: a peer that only ever
                                    sends unbatched messages (or the
                                    legacy "msg_batch" form) interoperates
                                    unchanged (reference: gRPC stream
                                    write coalescing in
                                    direct_task_transport.cc).

Object descriptors (Descr) carry values between processes:
  ("inline", bytes)                 pickled value, small
  ("shm", name, size, store_id)     shared-memory segment (zero-copy mmap,
                                    attachable only by processes sharing the
                                    creating host's object store)
  ("parts", meta, [bytes...])       serialized parts shipped over the wire —
                                    the cross-node transfer form (reference:
                                    object_manager.h:206 chunked push/pull)
  ("error", bytes)                  pickled exception

Transport: same message set over an AF_UNIX socket (workers on the head
host) or TCP (node agents and the workers they spawn on other hosts) —
the reference speaks gRPC for both (``node_manager.proto``).

The grammar above is narrative; the AUTHORITATIVE contract is the
``VERBS`` catalog below (verb → sender/handler roles, arity, capability
gate, doc — our one-file analog of the reference's 22 proto schemas).
``python -m ray_tpu.devtools.protocheck`` statically cross-checks every
send and handle site in the tree against it, and ``protocheck --doc``
renders it as the README's wire-protocol table.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import NamedTuple, Optional, Tuple


class Verb(NamedTuple):
    """One wire verb's contract — the machine-checked half of the
    docstring above (``ray_tpu.devtools.protocheck`` cross-checks every
    send and handle site against this catalog; ``protocheck --doc``
    renders it as the README's wire-protocol table).

    ``senders``/``handlers`` name module roles (head = runtime/head_main,
    worker = worker_main/direct, client, agent = node_agent, objsrv =
    object_transfer/shm_store).  ``arity`` is the legal tuple length
    INCLUDING the verb tag, as an inclusive (min, max) — ``None`` means
    deliberately variable (the per-exchange ``ok`` replies).  ``caps``
    names the capability family that must gate every send (the PR 3/6/7
    "never probe an old peer" convention).  ``external`` marks verbs
    whose peers live outside the analyzed tree (legacy spellings,
    dynamically-built envelopes) so the whole-program liveness check
    skips them."""

    senders: Tuple[str, ...]
    handlers: Tuple[str, ...]
    arity: Optional[Tuple[int, int]]
    doc: str
    caps: Optional[str] = None
    external: bool = False


VERBS = {
    # -- driver/head <-> worker control plane ------------------------------
    "exec": Verb(("head", "worker"), ("worker",), (2, 2),
                 "run a task / actor method (workers also self-enqueue "
                 "direct-pushed tasks under this tag)"),
    "create_actor": Verb(("head",), ("worker",), (2, 2),
                         "instantiate an actor class on this worker"),
    "func": Verb(("head",), ("worker",), (3, 3),
                 "function/class definition (cloudpickle)"),
    "obj": Verb(("head",), ("worker", "client"), (4, 4),
                "reply to a worker getparts"),
    "mgot": Verb(("head",), ("worker", "client"), (3, 3),
                 "reply to a batched mget"),
    "waited": Verb(("head",), ("worker", "client"), (3, 3),
                   "reply to a wait"),
    "reply": Verb(("head",), ("worker", "client"), (3, 3),
                  "generic request reply (store_addr, state_req, jobs, "
                  "actor requests, v1 lease_req)"),
    "free_segment": Verb(("head",), ("worker",), (4, 4),
                         "owner freed a segment this worker created; "
                         "pool pages iff reusable"),
    "kill": Verb(("head",), ("worker",), (1, 1), "graceful shutdown"),
    "steal": Verb(("head",), ("worker",), (3, 3),
                  "reclaim queued-but-unstarted task ids from a worker"),
    "ready": Verb(("worker",), ("head",), (4, 4),
                  "worker hello: id, pid, direct-server address"),
    "result": Verb(("worker",), ("head",), (5, 5),
                   "task finished: id, ok, returns, meta"),
    "result_batch": Verb(("worker",), ("head",), (2, 2),
                         "coalesced results (one pickle+write)"),
    "spans": Verb(("worker",), ("head",), (2, 2),
                  "task execution spans (ray timeline)"),
    "event": Verb(("worker",), ("head",), (3, 3),
                  "generic worker->driver pubsub (train streaming)"),
    "xfer_stats": Verb(("worker",), ("head",), (2, 2),
                       "periodic data-plane/lease counter deltas"),
    "getparts": Verb(("worker",), ("head",), (3, 3),
                     "fetch a remote segment's serialized parts"),
    "wait": Verb(("worker",), ("head",), (5, 5),
                 "blocking wait on object ids"),
    "mget": Verb(("worker", "client"), ("head",), (4, 4),
                 "batched get"),
    "submit": Verb(("worker", "client"), ("head",), (3, 3),
                   "nested task submission (fire-and-forget)"),
    "submit_batch": Verb(("worker", "client"), ("head",), (2, 2),
                         "bulk nested submission (one registration "
                         "pass)"),
    "resubmit_batch": Verb(("worker", "client"), ("head",), (2, 2),
                           "failover replay of retained head-routed "
                           "specs (head filters for at-least-once)"),
    "put": Verb(("client",), ("head",), (4, 4),
                "small inline client put (rides the put conflation "
                "buffer)"),
    "put_parts": Verb(("client",), ("head",), (5, 5),
                      "legacy client put: whole value in one control "
                      "message, head assembles"),
    "put_commit": Verb(("client",), ("head",), (4, 4),
                       "direct put: payload already streamed into the "
                       "destination store; O(1) descriptor "
                       "registration"),
    "addref": Verb(("worker", "client"), ("head",), (2, 2),
                   "object refcount +1"),
    "decref": Verb(("worker", "client"), ("head",), (2, 2),
                   "object refcount -1 (aggregate head ref of a "
                   "delegated object)"),
    "decref_batch": Verb(("worker", "client"), ("head",), (2, 2),
                         "buffered ref drops"),
    "addref_batch": Verb(("worker", "client"), ("head",), (2, 2),
                         "buffered ref bumps (nested ids in results)"),
    "actor_addref": Verb(("worker", "client"), ("head",), (2, 2),
                         "actor-handle refcount +1 (pickle-time)"),
    "actor_decref_batch": Verb(("worker", "client"), ("head",), (2, 2),
                               "buffered actor-handle ref drops"),
    "actor_token_new": Verb(("worker", "client"), ("head",), (3, 3),
                            "actor handle serialized (borrow token)"),
    "actor_token_used": Verb(("worker", "client"), ("head",), (3, 3),
                             "borrowed actor handle deserialized"),
    "actor_addr_req": Verb(("worker", "client"), ("head",), (3, 3),
                           "resolve an actor's direct-channel address"),
    "blocked": Verb(("worker",), ("head",), (2, 2),
                    "worker blocked in get/wait (lend the slot)"),
    "unblocked": Verb(("worker",), ("head",), (2, 2),
                      "worker resumed from get/wait"),
    "stolen": Verb(("worker",), ("head",), (3, 3),
                   "reply to a steal: task ids actually reclaimed"),
    "store_addr": Verb(("worker",), ("head",), (3, 3),
                       "resolve a store's object-server address "
                       "(+ caps)"),
    "state_req": Verb(("worker", "client"), ("head",), (4, 4),
                      "state introspection query (ray status/list)"),
    "kill_actor_req": Verb(("worker", "client"), ("head",), (4, 4),
                           "ray.kill(actor)"),
    "get_actor_req": Verb(("worker", "client"), ("head",), (4, 4),
                          "ray.get_actor(name)"),
    "create_actor_req": Verb(("worker", "client"), ("head",), (4, 4),
                             "synchronous actor creation request"),
    "cluster_info": Verb(("worker", "client"), ("head",), (2, 2),
                         "nodes/resources snapshot"),
    "get_package": Verb(("worker",), ("head",), (3, 3),
                        "fetch a working_dir package by id"),
    "job_submit": Verb(("client",), ("head",), (5, 5),
                       "job API: submit entrypoint"),
    "job_status": Verb(("client",), ("head",), (3, 3),
                       "job API: status"),
    "job_logs": Verb(("client",), ("head",), (3, 3), "job API: logs"),
    "job_stop": Verb(("client",), ("head",), (3, 3), "job API: stop"),
    "job_list": Verb(("client",), ("head",), (2, 2), "job API: list"),
    "actor_checkpoint": Verb(("worker",), ("head",), (3, 4),
                             "latest __ray_save__ descriptor from a "
                             "restartable actor; the optional 4th "
                             "element marks a drain-FORCED reply (parts "
                             "the head re-homes on a surviving store, "
                             "or None for a hookless actor) and is what "
                             "releases the drain's rendezvous"),
    "checkpoint_now": Verb(("head",), ("worker",), (2, 2),
                           "drain: force an immediate __ray_save__ of "
                           "the named actor, shipped as parts so the "
                           "head re-homes it on a surviving store; the "
                           "worker always replies actor_checkpoint "
                           "(None without the hook) so the drain never "
                           "stalls"),
    # -- lease plane (decentralized dispatch) ------------------------------
    "lease_req": Verb(("worker", "client"), ("head",), (4, 5),
                      "worker/client asks for leases; optional opts "
                      "dict {v:1, hint} selects the v1 dict reply"),
    "lease_grant": Verb(("head",), ("worker", "client"), (6, 6),
                        "unsolicited bulk grant piggybacked on a "
                        "head-brokered submit burst", caps="lease_v1"),
    "lease_renew": Verb(("worker",), ("head",), (2, 2),
                        "holder liveness, one message per N leased "
                        "pushes"),
    "lease_return": Verb(("worker",), ("head",), (2, 2),
                         "holder done with a leased worker"),
    "lease_revoke": Verb(("head",), ("worker", "client"), (2, 2),
                         "leased worker gone (node death / TTL "
                         "expiry)"),
    "dspill": Verb(("worker",), ("worker",), (3, 3),
                   "executor -> holder: pushed task bounced (queue over "
                   "lease_spillback_depth)"),
    # -- direct plane (worker <-> worker actor/lease channels) -------------
    "dexec": Verb(("worker",), ("worker",), (3, 3),
                  "push one task over a lease/actor channel"),
    "dexec_batch": Verb(("worker",), ("worker",), (2, 2),
                        "coalesced dexec frames (per-lease conflation "
                        "sender)"),
    "dfunc": Verb(("worker",), ("worker",), (3, 3),
                  "function definition rides the direct channel"),
    "dfree": Verb(("worker",), ("worker",), (4, 4),
                  "owner freed a segment the executor created"),
    "dmsg": Verb(("worker",), ("worker",), (3, 3),
                 "out-of-band payload on an actor channel "
                 "(collectives)"),
    "dresult": Verb(("worker",), ("worker",), (5, 5),
                    "direct task result (rid, ok, returns, meta)"),
    "dresult_batch": Verb(("worker",), ("worker",), (2, 2),
                          "coalesced direct results"),
    "dping": Verb(("worker",), ("worker",), (2, 2),
                  "holder -> executor channel-liveness probe: a lease/"
                  "actor channel with in-flight pushes and no traffic "
                  "for net_stall_timeout_s gets one; the executor's "
                  "connection thread answers dpong even while the task "
                  "computes, so a long task is never mistaken for a "
                  "stalled link"),
    "dpong": Verb(("worker",), ("worker",), (2, 2),
                  "executor -> holder reply to dping; any channel "
                  "traffic (this included) resets the holder's stall "
                  "clock"),
    # -- worker-ownership plane (direct path, via head) --------------------
    "export_obj": Verb(("worker",), ("head",), (2, 2),
                       "delegate worker-owned objects to the head "
                       "directory"),
    "export_complete": Verb(("worker",), ("head",), (2, 2),
                            "delegated export descriptors are final"),
    "descr_update": Verb(("worker",), ("head",), (2, 2),
                         "owner-side descriptor moves (spill/restore)"),
    "free_remote": Verb(("worker",), ("head",), (4, 4),
                        "unlink a segment homed in another node's "
                        "store"),
    # -- node-agent plane --------------------------------------------------
    "agent_ready": Verb(("agent",), ("head",), (2, 2),
                        "agent hello: node info + advertised "
                        "object_caps"),
    "agent_ack": Verb(("head",), ("agent",), (4, 4),
                      "agent handshake reply: node id, session, "
                      "config"),
    "spawn_worker": Verb(("head",), ("agent",), (3, 3),
                         "fork a worker on this node with env "
                         "overrides"),
    "kill_worker": Verb(("head",), ("agent",), (2, 2),
                        "terminate a worker process"),
    "kill_worker_hard": Verb(("head",), ("agent",), (2, 2),
                             "SIGKILL a worker (chaos/OOM paths)"),
    "read_segment": Verb(("head",), ("agent",), (3, 3),
                         "relay-read a segment from the agent's store"),
    "unlink_segment": Verb(("head",), ("agent",), (3, 3),
                           "free a segment in the agent's store"),
    "shutdown": Verb(("head",), ("agent",), (1, 1),
                     "tear the node down"),
    "segment": Verb(("agent",), ("head",), (4, 4),
                    "reply to read_segment"),
    "oom_pressure": Verb(("agent",), ("head",), (2, 2),
                         "node memory fraction crossed the monitor "
                         "threshold"),
    # -- elastic pods: preemption-aware drain (caps family "drain_caps":
    # agents advertise it in agent_ready, the head advertises it back in
    # the agent_ack config dict — the PR 3 "never probe an old peer"
    # convention) --------------------------------------------------------
    "preempt_notice": Verb(("agent",), ("head",), (3, 3),
                           "agent got a preemption warning (SIGTERM / "
                           "provider poll / chaos preempt): drain this "
                           "node within deadline_s, then release it "
                           "with drain_node", caps="drain_caps"),
    "drain_node": Verb(("head",), ("agent",), (3, 3),
                       "head -> agent: node drained (leases revoked, "
                       "actors checkpointed to a surviving store, small "
                       "sole-copy objects migrated) — finish up and "
                       "exit cleanly; doubles as the preempt_notice "
                       "ack and the graceful scale-down order",
                       caps="drain_caps"),
    "worker_logs": Verb(("agent",), ("head",), (2, 2),
                        "batched worker stdout/stderr lines"),
    # -- failure detection (gray failures; reference:
    # GcsHealthCheckManager + per-RPC gRPC deadlines).  Heartbeats are
    # the liveness FLOOR under the existing periodic traffic
    # (xfer_stats, renewals): a peer with nothing else to say still
    # sends one per health_check_period_s, so head-side silence is a
    # signal.  All four verbs are sent only while the
    # ``failure_detection`` switch is on (both sides read the same
    # plumbed knob, so an off-switch cluster never sees them). --------
    "heartbeat": Verb(("worker", "client", "agent"), ("head",), (2, 2),
                      "periodic liveness floor (worker/store id); also "
                      "the immediate reply to an hc_probe"),
    "hc_probe": Verb(("head",), ("worker", "agent"), (2, 2),
                     "suspicion probe: the peer's reader replies "
                     "heartbeat immediately even while its main thread "
                     "computes — differential observation of the LINK, "
                     "not the process"),
    "hc_ping": Verb(("worker", "client"), ("head",), (2, 2),
                    "head-connection watchdog probe: a worker/client "
                    "stuck waiting on a silent head sends one; the "
                    "head answers with a generic reply — continued "
                    "silence means the conn is stalled and the "
                    "watchdog closes it into the reconnect-and-replay "
                    "path"),
    # -- handshakes / failover ---------------------------------------------
    "client_ready": Verb(("client",), ("head",), (2, 2),
                         "client hello (nonce)"),
    "client_ack": Verb(("head",), ("client",), (2, 3),
                       "client handshake reply; the 3rd element "
                       "(direct-put bootstrap info dict) is absent from "
                       "old heads"),
    "reregister": Verb(("worker", "client"), ("head",), (2, 2),
                       "failover re-registration (workers, clients, "
                       "reconnecting agents' workers)"),
    "reregister_ack": Verb(("head",), ("worker",), (2, 2),
                           "re-registration accepted"),
    "reregister_nack": Verb(("head",), ("worker",), (1, 1),
                            "re-registration refused (unknown "
                            "session)"),
    # -- object-server data plane (capability-gated verbs) -----------------
    "fetch": Verb(("objsrv",), ("objsrv",), (2, 2),
                  "stream a whole segment"),
    "fetch_range": Verb(("objsrv",), ("objsrv",), (4, 4),
                        "stream one byte-range stripe; first stripe "
                        "doubles as the size probe", caps="object_caps"),
    "reserve_put": Verb(("objsrv",), ("objsrv",), (3, 3),
                        "preallocate the destination segment for a "
                        "direct put", caps="object_caps"),
    "put_range": Verb(("objsrv",), ("objsrv",), (4, 4),
                      "one byte-range stripe of a pending put",
                      caps="object_caps"),
    "commit_put": Verb(("objsrv",), ("objsrv",), (2, 2),
                       "seal a pending put", caps="object_caps"),
    "abort_put": Verb(("objsrv",), ("objsrv",), (2, 2),
                      "tear down a pending put", caps="object_caps"),
    "close": Verb(("objsrv",), ("objsrv",), (1, 1),
                  "end this object-server connection"),
    "ok": Verb(("objsrv",), ("objsrv",), None,
               "per-exchange success reply (shape varies by request; "
               "consumed inline by the requester, not via a dispatch "
               "chain)", external=True),
    "err": Verb(("objsrv",), ("objsrv",), (2, 2),
                "per-exchange failure reply (consumed inline)",
                external=True),
    # -- envelopes ---------------------------------------------------------
    "batch": Verb(("head", "worker", "client", "agent"),
                  ("head", "worker", "client", "agent"), (2, 2),
                  "N back-to-back messages as one pickle+write "
                  "(built dynamically by make_batch)", external=True),
    "msg_batch": Verb(("head", "worker", "client", "agent"),
                      ("head", "worker", "client", "agent"), (2, 2),
                      "legacy batch-envelope spelling from old peers",
                      external=True),
}


def enable_nodelay(conn) -> None:
    """Disable Nagle on a TCP connection (no-op for AF_UNIX pipes).

    The protocol often issues back-to-back small sends on one socket
    (blocked + mget, decref_batch + submit); with Nagle on, the second
    write stalls until the peer's delayed ACK (~40ms) — the classic
    Nagle/delayed-ACK interaction that collapsed client-mode gets to
    ~26/s.  The reference's gRPC channels disable Nagle the same way."""
    import socket as _socket

    try:
        fd = os.dup(conn.fileno())
    except (OSError, AttributeError):
        return
    try:
        s = _socket.socket(fileno=fd)
    except OSError:
        os.close(fd)
        return
    try:
        # Options bind to the open file description, which the original
        # connection shares with this dup.
        s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    except OSError:
        pass  # AF_UNIX
    finally:
        s.close()


class NetTimeoutError(OSError):
    """A wire operation made zero progress for its whole deadline
    (stalled peer/link) or a dial never completed.  An ``OSError``
    subclass on purpose: every existing ``except (EOFError, OSError)``
    discovery site treats a stall exactly like a broken connection —
    which is the point of the failure-detection plane."""


# ------------------------------------------------- net-chaos seam --------
# The armed hook: callable(point, conn) -> None | "drop" | "dup", or
# None.  ``ray_tpu.chaos.ChaosNet`` installs it (controller methods in
# the driver/head, RAY_TPU_CHAOS_NET env rules in spawned workers/
# agents) to create gray failures AT this seam: delays, full stalls,
# silent drops (one-way partition), duplicates.  Cost unarmed: one
# module-global ``is None`` check per send/recv.
_NET_HOOK = None


def set_net_hook(fn) -> None:
    global _NET_HOOK
    _NET_HOOK = fn


def net_point(point: str, conn) -> Optional[str]:
    """Named net-chaos point for raw chunk streams (``chunk_send`` in
    the object servers/pushers); ``send``/``recv`` fire implicitly."""
    hook = _NET_HOOK
    if hook is not None:
        return hook(point, conn)
    return None


# ------------------------------------------------- net counters ----------
# Process-wide failure-detection counters (the deadline core is the one
# place every stall/retry/hedge flows through).  Workers and clients
# ship them to the head in the periodic xfer_stats deltas; the head
# merges its own process's values in transfer_stats().  All zero with
# failure_detection off.
_NET_STATS_LOCK = threading.Lock()  # lock-order: leaf
_NET_STATS = {"stall_timeouts": 0, "net_retries": 0, "hedged_fetches": 0}


def note_net_event(key: str, n: int = 1) -> None:
    with _NET_STATS_LOCK:
        _NET_STATS[key] = _NET_STATS.get(key, 0) + n


def net_stats() -> dict:
    with _NET_STATS_LOCK:
        return dict(_NET_STATS)


def _is_timeout_oserror(e: BaseException) -> bool:
    import errno

    return isinstance(e, OSError) and e.errno in (errno.EAGAIN,
                                                  errno.EWOULDBLOCK)


def is_stall(e: BaseException) -> bool:
    """Whether an exception is a zero-progress deadline trip — either
    the typed :class:`NetTimeoutError` or the raw EAGAIN ``OSError`` an
    armed ``set_conn_deadline`` socket raises from mid-stream
    ``recv_bytes_into``/``send_bytes`` syscalls."""
    return isinstance(e, NetTimeoutError) or _is_timeout_oserror(e)


def _conn_socket(conn):
    """A connection's underlying fd duplicated as a ``socket`` object
    (the caller closes it), or None when the conn has no fd / the fd is
    not a socket — callers then leave the conn on its legacy
    fully-blocking behavior."""
    import socket as _socket

    try:
        fd = os.dup(conn.fileno())
    except (OSError, AttributeError):
        return None
    try:
        return _socket.socket(fileno=fd)
    except OSError:
        os.close(fd)
        return None


def _set_deadline_opts(conn, timeout_s: Optional[float], opts) -> bool:
    import socket as _socket
    import struct as _struct

    s = _conn_socket(conn)
    if s is None:
        return False
    try:
        t = timeout_s or 0.0
        tv = _struct.pack("ll", int(t), int((t - int(t)) * 1e6))
        for opt in opts:
            s.setsockopt(_socket.SOL_SOCKET, opt, tv)
        return True
    except OSError:
        return False
    finally:
        s.close()


def set_conn_deadline(conn, timeout_s: Optional[float]) -> bool:
    """Arm a ZERO-PROGRESS deadline on a connection's underlying socket
    (``SO_RCVTIMEO`` + ``SO_SNDTIMEO``): every read/write syscall gets
    ``timeout_s`` to move at least one byte, so progress resets the
    clock at the kernel and only a fully stalled transfer dies.  A
    tripped deadline surfaces from the in-flight ``recv_bytes``/
    ``send_bytes`` as an EAGAIN ``OSError`` — convert at the call site
    (``recv_deadline`` / the object-transfer range loops) into
    :class:`NetTimeoutError`.  ``None``/``0`` clears.  Returns False
    (no-op) when the fd is not a socket — the conn then keeps its
    legacy fully-blocking behavior."""
    import socket as _socket

    return _set_deadline_opts(conn, timeout_s,
                              (_socket.SO_RCVTIMEO, _socket.SO_SNDTIMEO))


def set_send_deadline(conn, timeout_s: Optional[float]) -> bool:
    """Arm only the SEND half of the zero-progress deadline
    (``SO_SNDTIMEO``).  For long-lived direct channels whose reader
    legitimately idles between results: sends get bounded (a stalled
    peer errors the sender into the existing channel-death path) while
    the blocking reader keeps waiting forever, as it should."""
    import socket as _socket

    return _set_deadline_opts(conn, timeout_s, (_socket.SO_SNDTIMEO,))


def enable_keepalive(conn) -> None:
    """Arm TCP keepalive on a dialed connection so a peer that vanishes
    without a FIN (powered-off VM, dropped route) eventually errors out
    of even the legacy blocking paths (reference: gRPC channel
    keepalive).  No-op for AF_UNIX."""
    import socket as _socket

    s = _conn_socket(conn)
    if s is None:
        return
    try:
        s.setsockopt(_socket.SOL_SOCKET, _socket.SO_KEEPALIVE, 1)
        for opt, val in (("TCP_KEEPIDLE", 30), ("TCP_KEEPINTVL", 10),
                         ("TCP_KEEPCNT", 6)):
            if hasattr(_socket, opt):
                s.setsockopt(_socket.IPPROTO_TCP,
                             getattr(_socket, opt), val)
    except OSError:
        pass  # AF_UNIX
    finally:
        s.close()


def shutdown_conn(conn) -> None:
    """``shutdown(SHUT_RDWR)`` a connection's underlying socket, then
    nothing else — the caller still owns the close.  THE way to take a
    connection away from a thread parked inside a blocking ``recv``:
    on Linux, ``close()`` alone does NOT wake a thread already blocked
    in ``read()`` on the fd (it only drops this process's reference),
    while shutdown delivers an immediate EOF to it.  Every watchdog
    that retires a stalled connection (the direct-channel liveness
    probe, the worker's stalled-head watchdog) must go through this or
    its parked reader never runs the death/reconnect path."""
    s = _conn_socket(conn)
    if s is None:
        return
    import socket as _socket

    try:
        s.shutdown(_socket.SHUT_RDWR)
    except OSError:
        pass  # already disconnected
    finally:
        s.close()


def dial(address, authkey: Optional[bytes] = None,
         connect_timeout: Optional[float] = None):
    """Deadline-aware ``multiprocessing.connection.Client``: bounded
    connect (a dial to a black-holed address fails in
    ``net_connect_timeout_s``, not the kernel's ~2 min default),
    ``SO_KEEPALIVE`` armed, Nagle off, and the auth handshake bounded
    by the same window (an accepted-but-stalled listener cannot hang
    the dialer).  ``connect_timeout=None`` reads the config knob; with
    ``failure_detection`` off this is byte-identical to the legacy
    ``Client()`` dial."""
    from multiprocessing.connection import Client

    if isinstance(address, str) and address.startswith("tcp://"):
        address = parse_address(address)
    if connect_timeout is None:
        from ray_tpu._private.config import GLOBAL_CONFIG as _cfg

        connect_timeout = (_cfg.net_connect_timeout_s
                           if _cfg.failure_detection else 0.0)
    if not connect_timeout or connect_timeout <= 0:
        conn = Client(tuple(address) if isinstance(address, (tuple, list))
                      else address, authkey=authkey)
        enable_nodelay(conn)
        return conn

    import socket as _socket
    from multiprocessing.connection import (Connection, answer_challenge,
                                            deliver_challenge)

    try:
        if isinstance(address, (tuple, list)):
            s = _socket.create_connection(tuple(address),
                                          timeout=connect_timeout)
            try:
                s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            except OSError:
                pass
        else:
            s = _socket.socket(_socket.AF_UNIX)
            s.settimeout(connect_timeout)
            s.connect(address)
        try:
            s.setsockopt(_socket.SOL_SOCKET, _socket.SO_KEEPALIVE, 1)
        except OSError:
            pass
        s.settimeout(None)  # back to blocking; deadlines are per-op
    except (_socket.timeout, TimeoutError) as e:
        raise NetTimeoutError(
            f"dial to {address!r} timed out after "
            f"{connect_timeout}s") from e
    conn = Connection(s.detach())
    if authkey is not None:
        # Bound the handshake too: the listener accepted but its
        # process may be hung.
        set_conn_deadline(conn, connect_timeout)
        try:
            answer_challenge(conn, authkey)
            deliver_challenge(conn, authkey)
        except OSError as e:
            conn.close()
            if _is_timeout_oserror(e):
                raise NetTimeoutError(
                    f"auth handshake with {address!r} stalled past "
                    f"{connect_timeout}s") from e
            raise
        except EOFError:
            conn.close()
            raise
        finally:
            try:
                set_conn_deadline(conn, None)
            except OSError:
                pass
    enable_keepalive(conn)
    return conn


def send(conn, msg: tuple):
    hook = _NET_HOOK
    if hook is not None:
        verdict = hook("send", conn)
        if verdict == "drop":
            return
        if verdict == "dup":
            conn.send_bytes(pickle.dumps(msg, protocol=5))
    conn.send_bytes(pickle.dumps(msg, protocol=5))


def recv(conn) -> tuple:
    hook = _NET_HOOK
    if hook is not None:
        hook("recv", conn)
    return pickle.loads(conn.recv_bytes())  # noqa: RTL403 -- the deadline core's own primitive; deadlines arm via set_conn_deadline/recv_deadline


def recv_deadline(conn, timeout_s: Optional[float]) -> tuple:
    """``recv`` bounded by a zero-progress deadline: the peer gets
    ``timeout_s`` per syscall to move bytes (progress resets the
    clock); full silence raises :class:`NetTimeoutError`.  ``None``/
    ``<=0`` falls back to the plain blocking recv (the legacy path)."""
    if not timeout_s or timeout_s <= 0:
        return recv(conn)
    armed = set_conn_deadline(conn, timeout_s)
    try:
        return recv(conn)
    except OSError as e:
        if armed and _is_timeout_oserror(e):
            raise NetTimeoutError(
                f"recv stalled past {timeout_s}s") from e
        raise
    finally:
        if armed:
            try:
                set_conn_deadline(conn, None)
            except OSError:
                pass


# Batch-envelope tag (plus the pre-envelope spelling still emitted by old
# peers; both unwrap identically).
BATCH = "batch"
LEGACY_BATCH = "msg_batch"


def make_batch(msgs):
    """List of messages -> the cheapest single wire message: the message
    itself for a singleton, a ("batch", msgs) envelope otherwise."""
    if len(msgs) == 1:
        return msgs[0]
    return (BATCH, msgs)


def send_batch(conn, msgs) -> None:
    """Ship back-to-back messages as ONE pickle + one write (no-op for an
    empty list) — the wire-level amortization that keeps fan-out paths at
    ~O(n/batch) syscalls instead of O(n)."""
    if not msgs:
        return
    send(conn, make_batch(msgs))


def is_batch(msg) -> bool:
    return msg[0] == BATCH or msg[0] == LEGACY_BATCH


INLINE = "inline"
SHM = "shm"
PARTS = "parts"
SPILLED = "spilled"  # ("spilled", path, size, store_id): on-disk segment
ERROR = "error"


def format_address(addr) -> str:
    """Listener address -> env-var string ("tcp://host:port" or a path)."""
    if isinstance(addr, tuple):
        return f"tcp://{addr[0]}:{addr[1]}"
    return addr


def parse_address(s: str):
    """Env-var string -> Client()-compatible address."""
    if s.startswith("tcp://"):
        host, port = s[len("tcp://"):].rsplit(":", 1)
        return (host, int(port))
    return s
