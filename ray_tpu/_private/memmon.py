"""Node memory readings for the OOM monitor.

Reference: ``src/ray/common/memory_monitor.h`` — the raylet samples the
cgroup first and /proc second, and triggers the worker-killing policy
above a usage threshold.  Inside a container /proc/meminfo reports the
HOST's memory, so a cgroup-limited process would never appear under
pressure; we therefore prefer cgroup v2 ``memory.current``/``memory.max``
(v1 ``memory.usage_in_bytes``/``memory.limit_in_bytes`` as fallback) and
only then fall back to /proc/meminfo's MemAvailable, which accounts for
reclaimable page cache the way the kernel's own OOM heuristics do.
"""

from __future__ import annotations

from typing import Optional

CGROUP_V2_USAGE = "/sys/fs/cgroup/memory.current"
CGROUP_V2_LIMIT = "/sys/fs/cgroup/memory.max"
CGROUP_V2_STAT = "/sys/fs/cgroup/memory.stat"
CGROUP_V1_USAGE = "/sys/fs/cgroup/memory/memory.usage_in_bytes"
CGROUP_V1_LIMIT = "/sys/fs/cgroup/memory/memory.limit_in_bytes"
CGROUP_V1_STAT = "/sys/fs/cgroup/memory/memory.stat"

# v1 reports an effectively-unlimited cgroup as a huge number (the
# kernel's page-counter max); treat anything this large as "no limit".
_NO_LIMIT = 1 << 50


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path, encoding="utf-8") as f:
            raw = f.read().strip()
    except OSError:
        return None
    if raw == "max":  # cgroup v2 spelling of "unlimited"
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _read_inactive_file(stat_path: str) -> int:
    """Reclaimable file cache charged to the cgroup; subtracted from
    usage so cached pages don't read as pressure (the same working-set
    definition the kernel's and k8s' OOM accounting use)."""
    try:
        with open(stat_path, encoding="utf-8") as f:
            for line in f:
                if line.startswith("inactive_file ") or \
                        line.startswith("total_inactive_file "):
                    return int(line.rsplit(None, 1)[1])
    except (OSError, ValueError):
        pass
    return 0


def _cgroup_usage_fraction() -> Optional[float]:
    """Usage fraction from the cgroup limits, or None when the process
    is not memory-limited by a cgroup (no files, or limit "max")."""
    for usage_p, limit_p, stat_p in (
            (CGROUP_V2_USAGE, CGROUP_V2_LIMIT, CGROUP_V2_STAT),
            (CGROUP_V1_USAGE, CGROUP_V1_LIMIT, CGROUP_V1_STAT)):
        usage = _read_int(usage_p)
        limit = _read_int(limit_p)
        if usage is None or limit is None:
            continue
        if limit <= 0 or limit >= _NO_LIMIT:
            continue  # unlimited cgroup: host meminfo is the truth
        used = max(0, usage - _read_inactive_file(stat_p))
        return min(1.0, used / limit)
    return None


def memory_usage_fraction(test_file: str = "") -> float:
    """Fraction of node memory in use, 0.0-1.0.  ``test_file`` overrides
    with a literal float (test injection; absent/invalid reads as 0)."""
    if test_file:
        try:
            with open(test_file, encoding="utf-8") as f:
                return float(f.read().strip())
        except (OSError, ValueError):
            return 0.0
    frac = _cgroup_usage_fraction()
    if frac is not None:
        return frac
    total = avail = None
    try:
        with open("/proc/meminfo", encoding="utf-8") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total or avail is None:
        return 0.0
    return max(0.0, 1.0 - avail / total)
