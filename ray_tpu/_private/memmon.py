"""Node memory readings for the OOM monitor.

Reference: ``src/ray/common/memory_monitor.h`` — the raylet samples
/proc (cgroup-aware there) and triggers the worker-killing policy above a
usage threshold.  We read /proc/meminfo's MemAvailable, which already
accounts for reclaimable page cache the way the kernel's own OOM
heuristics do.
"""

from __future__ import annotations


def memory_usage_fraction(test_file: str = "") -> float:
    """Fraction of node memory in use, 0.0-1.0.  ``test_file`` overrides
    with a literal float (test injection; absent/invalid reads as 0)."""
    if test_file:
        try:
            with open(test_file, encoding="utf-8") as f:
                return float(f.read().strip())
        except (OSError, ValueError):
            return 0.0
    total = avail = None
    try:
        with open("/proc/meminfo", encoding="utf-8") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total or avail is None:
        return 0.0
    return max(0.0, 1.0 - avail / total)
