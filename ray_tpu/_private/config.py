"""Runtime configuration flags.

TPU-native equivalent of the reference's macro-generated config struct
(reference: ``src/ray/common/ray_config_def.h:22`` — ``RAY_CONFIG(type, name,
default)``, 780 lines of flags, overridable via ``RAY_<name>`` env vars).

We keep the same two properties — one flat flag namespace, env-var override —
but as a plain dataclass: every field can be overridden with
``RAY_TPU_<FIELD_NAME>`` in the environment, and programmatically via
``ray_tpu.init(_system_config={...})``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get("RAY_TPU_" + name.upper())
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


@dataclasses.dataclass
class Config:
    # Objects whose serialized size is below this are carried inline inside
    # protocol messages; larger ones go to the shared-memory store.  The
    # reference cutoff is 100KB (``max_direct_call_object_size``,
    # ray_config_def.h:212); we default higher because host pipes on a TPU VM
    # comfortably move 1MB messages and shm setup has fixed cost.
    # protocheck: env-alias RAY_TPU_MAX_INLINE -- legacy spelling read directly by worker_entry
    max_inline_object_size: int = 1024 * 1024

    # Shared-memory store capacity (bytes).  0 = unlimited (bounded by
    # /dev/shm).  Mirrors plasma's store size (object_manager/plasma/).
    # protocheck: head-only -- workers get their per-node slice as RAY_TPU_STORE_BYTES (head spawn env / agent-computed cap), not this knob
    object_store_memory: int = 0

    # Directory for shared-memory segments.
    # protocheck: head-only -- workers inherit the session store path via RAY_TPU_SHM_DIR_OVERRIDE from their node's store owner
    shm_dir: str = "/dev/shm"

    # Bytes of freed-but-still-mapped shm segments kept pooled for in-place
    # reuse (plasma-arena analog: fresh tmpfs pages fault+zero at ~1 GB/s,
    # pooled pages take writes at memcpy speed).  0 disables pooling.
    # protocheck: env-alias RAY_TPU_POOL_BYTES -- legacy spelling read directly by worker_entry/node_agent
    shm_pool_bytes: int = 1 << 30

    # --- Cross-node object transfer (the data-plane fast path;
    # reference: object_manager.h:206 chunked push/pull with multiple
    # transfers in flight, object_buffer_pool.h). ---
    # Connections kept per peer object server: concurrent fetches of
    # different segments ride separate pooled connections, and one large
    # segment stripes across them.
    object_pool_size: int = 4
    # Segments at least this big are fetched as concurrent byte-range
    # stripes of this length over multiple pooled connections (needs the
    # peer's "fetch_range" capability).  0 disables striping.
    object_stripe_threshold: int = 32 * 1024 * 1024
    # Host the HEAD advertises for its object server when binding
    # 0.0.0.0 (the hostname lookup fallback can resolve to 127.0.1.1 or
    # a NAT-internal address on some distros; node agents have the same
    # escape hatch via RAY_TPU_AGENT_ADVERTISE_HOST).  "" = derive from
    # listen_host.
    # protocheck: head-only -- names the HEAD's advertised object-server host; agents have RAY_TPU_AGENT_ADVERTISE_HOST
    object_advertise_host: str = ""

    # --- Direct puts (the WRITE-direction twin of the pooled/striped
    # pull path; reference: plasma CreateObject/Seal on a dedicated
    # store socket — writes never ride a GCS RPC).  Master switch: a
    # client/worker put of a value destined for another store pushes
    # the payload over the data plane (reserve_put/put_range/commit_put
    # on the destination's object server) and sends only an O(1)
    # ("put_commit", ...) control message.  Off = the legacy whole-value
    # ("put_parts", ...) control message, byte-identical, with every
    # direct-put counter zero. ---
    direct_puts: bool = True
    # A pushed value at least this big is streamed as concurrent
    # byte-range stripes of this length over multiple pooled
    # connections (needs the peer's "put_range" capability); smaller
    # direct puts stream whole on one pooled connection.  0 disables
    # striping (whole-value streams only).
    object_put_stripe_threshold: int = 32 * 1024 * 1024
    # Connections kept per destination object server for pushes.  0 =
    # inherit object_pool_size (one sizing knob for both directions).
    object_put_pool_size: int = 0

    # --- Locality-aware scheduling (reference:
    # scheduling/policy/hybrid_scheduling_policy.cc — lease selection
    # prefers the node holding the task's argument bytes).  The default
    # policy scores candidate nodes by argument bytes homed in their
    # object store and prefers the top-locality node that fits; it never
    # stalls a class (a preferred-but-full node just falls back to the
    # head-first order, counted in ``locality_misses``).
    # protocheck: head-only -- placement scoring runs in the head scheduler only
    locality_scheduling: bool = True
    # Minimum bytes of node-homed argument data before locality overrides
    # the head-first placement order (below it, transfer is cheaper than
    # disturbing the packing).
    # protocheck: head-only -- placement scoring runs in the head scheduler only
    locality_min_bytes: int = 1024 * 1024

    # --- Pipelined argument prefetch (reference: raylets pull task
    # dependencies before the worker starts so transfer overlaps
    # compute).  While a worker computes, up to this many concurrent
    # pulls materialize the REMOTE shm args of tasks queued behind it;
    # ``_load_args`` then consumes the prefetched segments.  Also caps
    # the concurrent pulls _load_args itself issues for a multi-arg
    # task.  0 disables prefetching (args materialize serially on the
    # task's critical path, the pre-PR behavior).
    arg_prefetch_depth: int = 2

    # --- ray_tpu.data streaming execution (reference:
    # python/ray/data/_internal/execution/streaming_executor.py — operator
    # graph with resource-budgeted admission). ---
    # Master switch for the backpressured operator-graph executor behind
    # Dataset._stream_refs.  Off = the pre-PR windowed chain-submission
    # path, byte-identical, with every streaming counter zero.
    streaming_executor: bool = True
    # Global in-flight byte budget for a streaming execution: queued
    # intermediate blocks + estimated in-flight task output.  0 = auto,
    # data_memory_budget_fraction of the object-store capacity (the
    # store's configured cap, else the shm filesystem size).
    data_memory_budget: int = 0
    data_memory_budget_fraction: float = 0.25
    # Cap on concurrently in-flight streaming tasks across all operators
    # (admission is primarily byte-budgeted; this bounds task/worker
    # fan-out for tiny-block datasets).  0 = auto: the cluster's total
    # CPU count (min 1, fallback 8 when it cannot be read).
    data_max_inflight_tasks: int = 0

    # --- Push-based distributed shuffle (reference: Exoshuffle
    # (SIGCOMM'23) push-based map output + Ownership (NSDI'21)
    # pipelined operators).  Master switch for the push-based
    # all-to-all shuffle behind Dataset.sort/random_shuffle and
    # GroupedDataset.aggregate/map_groups: map tasks partition rows
    # and push each partition straight into its reducer's node store
    # over the striped put verbs (reserve_put/put_range/commit_put),
    # reducers merge on arrival.  Off = the pre-PR map/reduce fan-out,
    # byte-identical, with every shuffle counter zero.  Read in the
    # WORKER process (map tasks + reducer actors), so it rides
    # _worker_config_env. ---
    push_shuffle: bool = True
    # Target bytes per shuffle partition for sort/groupby: the planner
    # picks the reducer count R ~ total_bytes / target (clamped to
    # [1, 4 * n_blocks]).  0 = one reducer per input block (R =
    # n_blocks), which random_shuffle always uses so its seeded
    # permutation is reproducible across the switch.
    shuffle_partition_bytes_target: int = 0
    # Streaming-merge fan-in for sort reducers: once at least this many
    # sorted runs have arrived, the reducer k-way merges them into one
    # (heapq.merge, stable on (map_idx, pos) ties) so memory tracks the
    # run count, not the input count.  Also bounds the merge at
    # finalize.  Minimum 2.
    shuffle_merge_fanin: int = 8

    # --- Distributed training (reference: PipeDream SOSP'19 1F1B +
    # IMPALA ICML'18 decoupled actor/learner).  Master switch for the
    # distributed training planes: pipeline stages as long-lived
    # restartable actors exchanging micro-batch activations/grads over
    # the striped put verbs with the 1F1B schedule driven by the actor
    # call pipeline (train/pipeline_actors.py), and IMPALA's aggregator
    # actors + host->TPU double-buffered learner queue (rllib/impala.py).
    # Off = the byte-identical single-host paths (pipeline_apply in one
    # process, the per-batch direct learner update) with every new
    # counter (microbatch_pushes / stage_restarts / learner_queue_stalls)
    # zero.  Read in WORKER processes too (stage actors push; a trainer
    # built inside a Trainable must see the driver's switch), so it
    # rides _worker_config_env. ---
    distributed_training: bool = True
    # Default micro-batch count for PipelineTrainer when the caller does
    # not pass one: 0 = 2 * num_stages (the 1F1B sweet spot — enough
    # in-flight microbatches to hide the pp-1 fill, bounded stash).
    pipeline_microbatches: int = 0
    # Host->device queue depth for IMPALA's learner loader thread (the
    # MultiGPULearnerThread analog): batch t+1's h2d transfer is issued
    # while step t computes, up to this many device-resident batches
    # buffered ahead.  0 disables the loader thread (each update pays
    # its own h2d on the critical path — the measured A/B baseline).
    impala_queue_depth: int = 2

    # --- Decentralized dispatch (reference: the raylet's lease-based
    # hybrid scheduling, RequestWorkerLease + spillback in
    # local_task_manager.h:58, with task metadata owned by the submitting
    # worker — Ownership, NSDI'21).  Master switch for the lease-grant
    # scheduling plane: bulk lease grants piggybacked on head-brokered
    # submit bursts, holder-side renewal batching, executor spillback,
    # lease revocation on node death, and the head's sharded/deferred
    # dispatch passes.  Off = the pre-existing head-brokered path,
    # byte-identical, with every decentralized-dispatch counter zero. ---
    decentralized_dispatch: bool = True
    # Execution slots per granted lease: the holder pipelines at most this
    # many unacked pushes onto one leased worker (capped by
    # max_tasks_in_flight_per_worker at grant time).
    lease_slots: int = 8
    # Lease time-to-live: the head revokes (and retires) a client-leased
    # worker whose holder has not renewed within this window — the
    # holder's liveness signal, since pushed tasks never touch the head.
    # 0 disables TTL expiry (leases then end only via return/death).
    lease_ttl_s: float = 15.0
    # Holder-side renewal cadence: one ("lease_renew", ...) message per
    # this many leased pushes (plus a periodic renew for long tasks) —
    # the "one message per N tasks" amortization.
    lease_renew_tasks: int = 64
    # Executor-side spillback: a pushed (spill-eligible) task arriving
    # while the worker's local queue is at least this deep bounces back
    # to the holder with a next-best-node hint instead of queueing
    # (reference: hybrid policy spillback).  0 disables spillback.
    lease_spillback_depth: int = 32

    # --- Serving (ray_tpu.serve; reference: Orca OSDI'22 iteration-level
    # scheduling + serve autoscaling_policy.py). ---
    # Master switch for the continuous-batching engine behind
    # @serve.batch(mode="continuous"): on, queued requests are admitted
    # into the RUNNING batch at step boundaries and finished requests'
    # slots refill the same step.  Off = the same step function driven
    # one-shot (fixed batch admitted only when the previous one fully
    # finished — the legacy window semantics), the measured A/B
    # baseline.  Read in the REPLICA process (rides _worker_config_env).
    continuous_batching: bool = True
    # --- Serving memory plane (reference: vLLM PagedAttention SOSP'23 +
    # Leviathan et al. ICML'23 speculative decoding). ---
    # Master switch for the paged KV cache: a deployment that attaches a
    # kv_cache.PagedKVEngine gets block-granular admission (a request is
    # admitted when its KV BLOCKS fit, not a max-length slot) and the
    # paged decode mode in replicas that support it
    # (serve/tpu_replica.py).  Off = the byte-identical PR 8 dense
    # engine: the attached engine is ignored, every serving-memory
    # counter (kv_blocks_* / prefix_* / spec_* / cow_copies) stays zero.
    # Read in the REPLICA process (rides _worker_config_env).
    paged_kv: bool = False
    # Shared-prefix reuse on the paged cache: prompt-prefix-hash keyed
    # block chains with refcounts and copy-on-write divergence; requests
    # sharing a system prompt map the same physical blocks.  Only
    # meaningful with paged_kv on.
    prefix_caching: bool = True
    # Speculative decoding: a draft model proposes this many tokens per
    # step and the target verifies them in one batched forward
    # (exact-match acceptance keeps greedy output bitwise-unchanged).
    # 0 disables.  Only meaningful with paged_kv on, read by replicas
    # that implement a draft path.
    speculative_k: int = 0
    # Autoscale smoothing: the controller scales on each handle's PEAK
    # ongoing-request count inside this look-back window.
    serve_metric_lookback_s: float = 3.0
    # Default quiet period before a deployment downscales (an explicit
    # autoscaling_config downscale_delay_s overrides it per deployment).
    serve_downscale_delay_s: float = 5.0
    # --- Disaggregated serving (reference: DistServe OSDI'24 /
    # Splitwise ISCA'24). ---
    # Master switch for the prefill/decode pool split: a capable
    # deployment (replicas exporting prefill_export / disagg_generate)
    # is deployed as two pools behind one logical name — prefill
    # replicas run prompt-only steps and hand the finished KV block
    # chain to a decode replica as a segment image streamed over the
    # reserve_put/put_range data plane.  Off = the byte-identical
    # monolithic engine: one pool, prefill interleaved with decode,
    # every disaggregation counter (kv_chains_* /
    # kv_chain_bytes_streamed / router_prefix_*) stays zero.  Read in
    # the REPLICA and PROXY processes (rides _worker_config_env).
    disaggregated_serving: bool = False
    # Stripe threshold for streamed KV chains: a chain segment larger
    # than this is striped across put-pool connections (put_range),
    # smaller ones go single-stream.  Chains are typically much larger
    # than generic task args, so this defaults lower than
    # object_put_stripe_threshold.  Read wherever a prefill replica
    # pushes (rides _worker_config_env).
    kv_stream_stripe_threshold: int = 1 << 18
    # Prefix-affinity routing on top of power-of-two-choices: handles
    # score prefill replicas by the longest prompt-chunk chain they
    # recently served (route to where the PrefixCache already holds the
    # blocks; p2c on miss).  Only meaningful with
    # disaggregated_serving on — all router_prefix_* counters stay
    # zero when the split is off.
    prefix_affinity: bool = True

    # Seconds a worker may sit idle before the pool reaps it (reference:
    # idle worker killing in worker_pool.cc).
    # protocheck: head-only -- the idle-worker reaper runs in the head's pool
    idle_worker_timeout_s: float = 300.0

    # Soft cap on extra workers spawned when existing workers block in
    # ``ray.get`` (reference: worker cap w/ backoff, ray_config_def.h:174-187).
    # protocheck: head-only -- blocked-worker cap enforced by the head's spawn path
    max_extra_blocked_workers: int = 16

    # Task retry default (reference: max_retries=3 for normal tasks).
    # protocheck: head-only -- retry budgets are seeded at head registration (direct-path specs carry explicit max_retries)
    default_max_retries: int = 3

    # Tasks pipelined onto one leased worker before a new worker is leased
    # (reference: max_tasks_in_flight_per_worker in
    # direct_task_transport.h:75 — kills the per-task result round trip).
    # protocheck: head-only -- the pipeline bound is applied at grant time; holders receive it as the grant's slots field
    max_tasks_in_flight_per_worker: int = 10

    # --- Failure detection (gray failures: alive-but-hung peers;
    # reference: per-RPC gRPC deadlines + GcsHealthCheckManager with
    # health_check_initial_delay_ms / timeout / period /
    # failure_threshold in ray_config_def.h; "Gray Failure: The
    # Achilles' Heel of Cloud-Scale Systems", HotOS'17 — differential
    # observation, peer-observed stalls rather than process liveness).
    # Master switch for the whole plane: deadlines on every wire
    # operation (connect timeouts + SO_KEEPALIVE on every dial,
    # zero-progress stall deadlines on transfers with
    # progress-resets-the-clock semantics, transport retries with
    # backoff+jitter), worker/agent heartbeat floors, the head's
    # suspicion state machine (SUSPECT -> probe -> DEAD), and the
    # direct-channel liveness probes.  Off = the legacy fully-blocking
    # behavior, byte-identical, with every new counter
    # (stall_timeouts / net_retries / hedged_fetches / suspected_nodes)
    # zero. ---
    failure_detection: bool = True
    # Zero-progress deadline for one wire operation: a transfer that
    # moves no bytes for this long is declared stalled (each received/
    # sent chunk resets the clock, so a slow-but-moving stripe is never
    # killed while a fully stalled one dies right here).  Also bounds
    # reply waits on request/reply exchanges and the direct-channel
    # liveness probe window.
    net_stall_timeout_s: float = 15.0
    # Connect timeout for every dial (object-transfer pools, direct
    # channels, client/agent/worker head dials).  Without it a dial to
    # a black-holed address blocks for the kernel default (~2 min).
    net_connect_timeout_s: float = 5.0
    # Transport-level retry budget for one stalled/broken pull or push:
    # the broken pooled connection is evicted and the transfer retried
    # up to this many times before the loss surfaces as a structured
    # (reconstructable) ObjectLostError(phase="stalled") and the caller
    # hedges to the relay/reconstruction fallbacks.
    net_retry_count: int = 2
    # Base backoff between transport retries; attempt k sleeps
    # base * 2^k plus up to 50% random jitter.
    net_retry_backoff_base_ms: float = 50.0
    # Health-check cadence (reference: GCS pull-based health checks,
    # gcs_health_check_manager.h:39): the head's suspicion loop ticks at
    # this period, and it is the worker/agent heartbeat floor — a peer
    # with no other head traffic sends one ("heartbeat", ...) per
    # period, so silence is a signal, not an idle link.
    health_check_period_s: float = 5.0
    # Silence (no message from a node's agent / a worker) longer than
    # this marks the peer SUSPECT and starts probing it.
    health_check_timeout_s: float = 15.0
    # A SUSPECT peer that misses this many consecutive probe windows is
    # declared DEAD and fed to the existing node/worker-death path —
    # a stalled node becomes indistinguishable from a killed one within
    # one suspicion window.
    health_check_failure_threshold: int = 3
    # Grace added to a freshly registered peer's first deadline (boot,
    # env build, and JIT warmup all legitimately delay the first
    # heartbeat).
    health_check_initial_delay_s: float = 10.0

    # Wait this long for a worker process to start before declaring failure.
    # protocheck: head-only -- spawn timeout enforced by the head
    worker_start_timeout_s: float = 60.0

    # Number of workers prestarted at init when num_cpus not yet demanded
    # (reference: prestart in worker_pool.cc).
    # protocheck: head-only -- prestart happens at head init
    prestart_workers: int = 0

    # Multiprocessing start method: "forkserver" is fastest that is still
    # safe with JAX in the driver ("fork" is not — XLA runtime threads).
    # protocheck: head-only -- consumed by the head's process spawner
    worker_start_method: str = "forkserver"

    # --- Fault tolerance (reference: object_recovery_manager.h:41 +
    # task_manager.h:174 lineage pinning; Ownership, NSDI'21). ---
    # Master switch for the recovery subsystem: lineage recording +
    # object reconstruction (head-owned AND worker-owned), actor
    # state-checkpoint hooks, and the recovery counters.  Off = a lost
    # object surfaces ObjectLostError exactly as the legacy path did,
    # with reconstructions / reconstruction_failures / actor_restarts /
    # chaos_kills all zero.
    recovery: bool = True
    # Lineage-based object reconstruction: keep creating-task specs for
    # owned task returns; a lost object is rebuilt by re-executing its
    # task.  (Legacy escape hatch; ``recovery`` is the master switch.)
    lineage_enabled: bool = True
    # Byte budget for each owner's retained lineage (the head's table
    # and every worker's DirectCaller table independently): entries
    # evict oldest-first past it, mirroring the reference's
    # lineage-pinning cap (max_lineage_bytes).  Evicted lineage makes
    # the objects unrecoverable — recovery then refuses, it never
    # guesses.  0 = unbounded.
    lineage_bytes_budget: int = 64 * 1024 * 1024
    # Restartable actors: minimum seconds between automatic
    # __ray_save__ checkpoints of an actor that defines the hooks
    # (checkpoint bytes go through the object store, spill-aware).
    # 0 = checkpoint after every method call.
    actor_checkpoint_interval_s: float = 0.0

    # Where over-capacity shm objects spill (reference:
    # local_object_manager.h:41 spill to external storage).  Empty =
    # /tmp/ray_tpu_spill_<session>.
    # protocheck: head-only -- workers/agents get the session-resolved path via RAY_TPU_SPILL_DIR_OVERRIDE
    spill_dir: str = ""

    # Host the head's TCP listener binds (node agents + their workers dial
    # in here).  Use "0.0.0.0" for real multi-host clusters.
    # protocheck: head-only -- the head's own listener bind address
    listen_host: str = "127.0.0.1"

    # --- GCS-analog fault tolerance (reference: GCS table persistence via
    # redis, src/ray/gcs/store_client/redis_store_client.h:28, and the
    # GcsInitData load-on-restart path, gcs_server.h:77). ---
    # Snapshot file for head metadata (KV, functions, named actors, jobs).
    # "" disables snapshotting.
    # protocheck: head-only -- head snapshot machinery
    gcs_snapshot_path: str = ""
    # Snapshot cadence; dirty state is written at most this often.
    # protocheck: head-only -- head snapshot machinery
    gcs_snapshot_interval_s: float = 2.0
    # Load the snapshot at init (head restart): restores KV/functions and
    # re-creates named actors per their creation specs.
    # protocheck: head-only -- head restart restore switch
    gcs_restore: bool = False
    # Fixed TCP listener port (0 = ephemeral).  A restarting head must
    # rebind the old port so agents and clients can re-dial it.
    # protocheck: head-only -- the head's own listener port
    listen_port: int = 0
    # Fixed cluster authkey (hex; "" = random per session).  Needed across
    # head restarts so agents/clients can re-authenticate.
    # protocheck: head-only -- session authkey reaches workers as RAY_TPU_AUTHKEY in the spawn env
    authkey_hex: str = ""

    # --- Head failover (reference: workers reconnecting across a GCS
    # restart — gcs_rpc_server_reconnect_timeout_s /
    # gcs_failover_worker_reconnect_timeout, ray_config_def.h:62 — plus
    # per-owner metadata surviving the metadata server, Ownership
    # NSDI'21). ---
    # Master switch: on head-connection EOF, workers and clients PARK
    # in-flight head calls, re-dial with backoff, and re-register
    # (re-advertising owned objects, held leases, queued/running tasks,
    # and actor incarnations); node agents keep their workers ALIVE and
    # re-dial.  Off = today's behavior: a worker exits on head EOF and
    # an agent tears its workers down, so a head death is an outage.
    head_failover: bool = True
    # How long a disconnected peer (worker/client/agent) keeps re-dialing
    # the head before giving up — the failover grace window.  A peer that
    # exhausts it behaves as with the switch off (worker exit / agent
    # teardown); the head revokes whatever it was holding.
    head_reconnect_grace_s: float = 20.0
    # How long a RESTARTED head waits for restored nodes, leases, and
    # actor incarnations to be re-claimed by reconnecting peers before
    # reconciling the remainder: unclaimed leases are revoked (the PR 6
    # path), unclaimed restored actors are re-created from their last
    # __ray_save__ checkpoint, and unresolved blip-window objects fail
    # as reconstruction candidates.
    head_reregister_timeout_s: float = 10.0
    # Node agents re-dial a restarted head instead of exiting ("0"
    # disables — the previously-undocumented escape hatch, now paired
    # with head_failover: with failover on a reconnecting agent keeps
    # its workers; with it off it kills them first, the legacy
    # behavior).
    # protocheck: head-only -- agent-process knob, read from the agent's own environment (launcher/operator-set)
    agent_reconnect: bool = True

    # --- Elastic pods (preemption-aware drain + spot slice pools;
    # reference: the GCS DrainNode RPC + raylet drain,
    # gcs_node_manager.h / node_manager.cc HandleDrainRaylet — node
    # removal as a first-class protocol rather than a death). ---
    # Master switch for the drain protocol: scale-down and preemption
    # notices route through ``Runtime.drain_node`` (stop placements,
    # revoke leases, force-checkpoint restartable actors to a surviving
    # store, migrate small sole-copy objects) before the node goes
    # away.  Off = the legacy hard-remove path, byte-identical, with
    # every elastic counter (preemptions / drains_completed /
    # drain_timeouts / objects_migrated) zero.
    elastic_drain: bool = True
    # Wall-clock budget for one node drain (the spot warning window —
    # e.g. ~30s on GCE preemptible TPUs).  Past it the drain falls
    # through to the existing hard-kill recovery: lineage reconstructs
    # what migration did not cover.
    drain_deadline_s: float = 10.0
    # Sole-copy objects homed on a draining node at most this big are
    # migrated (pulled and re-homed on the head's surviving store);
    # larger ones stay behind as lineage-reconstruction candidates —
    # re-executing the producer beats moving a multi-GB value through
    # a closing warning window.
    drain_migrate_max_bytes: int = 64 * 1024 * 1024
    # Spot pool fallback: after this many observed preemptions of one
    # spot node type, the autoscaler stops preferring that type and
    # launches its on-demand fallback instead (per-type accounting in
    # StandardAutoscaler).
    spot_fallback_threshold: int = 2

    # --- OOM memory monitor (reference: src/ray/common/memory_monitor.h
    # + worker_killing_policy_group_by_owner.cc: kill the newest
    # retriable task's worker before the kernel OOM-killer takes the
    # node). ---
    # Node memory usage fraction above which the monitor kills one task
    # worker per interval.  0 disables.
    # protocheck: head-only -- monitor knobs reach node agents in the agent_ack config dict
    memory_monitor_threshold: float = 0.95
    # protocheck: head-only -- monitor knobs reach node agents in the agent_ack config dict
    memory_monitor_interval_s: float = 1.0
    # Test hook: read the usage fraction from this file instead of
    # /proc/meminfo (reference tests inject usage the same way).
    # protocheck: head-only -- monitor knobs reach node agents in the agent_ack config dict
    memory_monitor_test_file: str = ""

    # Stream worker stdout/stderr to the driver with a worker prefix
    # (reference: log_monitor.py + log_to_driver in ray.init).  Worker
    # output always lands in per-worker files under the session dir;
    # this flag controls the re-print at the driver.
    # protocheck: head-only -- the re-print of worker logs happens in the head's monitor thread
    log_to_driver: bool = True

    @classmethod
    def from_env(cls, overrides: dict | None = None) -> "Config":
        kwargs = {}
        for f in dataclasses.fields(cls):
            kwargs[f.name] = _env_override(f.name, f.default)
        if overrides:
            for k, v in overrides.items():
                if k not in kwargs:
                    raise ValueError(f"Unknown config flag: {k}")
                kwargs[k] = v
        return cls(**kwargs)


GLOBAL_CONFIG = Config.from_env()
