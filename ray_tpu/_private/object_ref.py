"""ObjectRef — a first-class future + distributed reference.

Reference analog: ``python/ray/includes/object_ref.pxi`` ObjectRef plus the
ownership model of ``src/ray/core_worker/reference_count.h:61`` (the caller
of a task owns its returns; refs are counted at the owner and freed when the
last handle drops).  Our refcounting protocol is deliberately simpler than
the reference's 1.6k-LoC borrowed-ref machinery: every ref increment/decrement
is routed to the owner's store (driver-resident in v1), and serializing a ref
into a task argument pins it until that task finishes.
"""

from __future__ import annotations

import weakref
from typing import Optional

from ray_tpu._private.ids import ObjectID

# Set by the worker/driver context at init; lets __del__ and pickling find
# the live runtime without import cycles.
_runtime_accessor = None


def _set_runtime_accessor(fn):
    global _runtime_accessor
    _runtime_accessor = fn


class ObjectRef:
    __slots__ = ("_id", "_owner_hint", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hint: str = "", *,
                 _register: bool = True):
        self._id = object_id
        self._owner_hint = owner_hint
        if _register and _runtime_accessor is not None:
            rt = _runtime_accessor()
            if rt is not None:
                rt.add_local_reference(object_id)

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    def future(self):
        """concurrent.futures.Future resolving to the object's value."""
        rt = _runtime_accessor() if _runtime_accessor else None
        if rt is None:
            raise RuntimeError("ray_tpu not initialized")
        return rt.object_future(self._id)

    def __await__(self):
        """asyncio integration (reference: ObjectRef.__await__ via
        asyncio.wrap_future)."""
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        # Serializing a ref (into task args or a put) notifies the runtime so
        # the object stays pinned while in flight (simplified borrowed-ref
        # protocol; reference: reference_count.cc borrower bookkeeping).
        rt = _runtime_accessor() if _runtime_accessor else None
        if rt is not None:
            rt.on_ref_serialized(self._id)
        return (_deserialize_ref, (self._id, self._owner_hint))

    def __del__(self):
        try:
            rt = _runtime_accessor() if _runtime_accessor else None
            if rt is not None:
                rt.remove_local_reference(self._id)
        except Exception:
            pass  # interpreter shutdown


def _deserialize_ref(object_id: ObjectID, owner_hint: str) -> ObjectRef:
    return ObjectRef(object_id, owner_hint)
