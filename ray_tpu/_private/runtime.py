"""Driver-resident runtime: object table, scheduler, worker pools, control plane.

This file is the TPU-native condensation of four reference components:

- GCS tables (actors, KV, nodes, placement groups) —
  ``src/ray/gcs/gcs_server/gcs_server.h:77`` and friends.  On a TPU pod the
  control plane is tiny relative to the data plane, so v1 keeps it as
  in-process tables with locks instead of a separate server process; the
  message surface (register/lookup/kv) matches so it can move out-of-process
  for multi-host (see node.py).
- Scheduling — ``src/ray/raylet/scheduling/cluster_task_manager.h:42`` +
  ``local_task_manager.h:58``.  We keep the reference's semantics (resource
  admission, queueing, spillback across nodes, placement-group bundle
  reservation 2-phase style) with a single scheduler since one driver owns
  submission in v1.
- Ownership + reference counting — ``src/ray/core_worker/reference_count.h:61``
  and ``task_manager.h:90`` (retries, error objects).  The driver owns every
  object; local refs, worker refs, and in-flight pins are counted here and
  the object (incl. its shm segment) is freed at zero.
- Worker pool — ``src/ray/raylet/worker_pool.h:156`` (spawn, cache by env,
  dedicated TPU workers, idle reaping).
"""

from __future__ import annotations

import atexit
import itertools
import multiprocessing
import multiprocessing.connection
import os
import sys
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import object_transfer, protocol, recovery, \
    serialization
from ray_tpu._private.config import Config
from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
    new_task_id,
)
from ray_tpu._private.shm_store import ShmStore
from ray_tpu import exceptions as exc

PENDING, READY, ERRORED = 0, 1, 2


class ObjectState:
    __slots__ = (
        "status", "descr", "local_refs", "worker_refs", "pins",
        "futures", "waiters", "task_id", "value", "has_value", "segment",
        "nested_ids", "shipped", "creator", "exporter",
    )

    def __init__(self, task_id: Optional[TaskID] = None):
        self.status = PENDING
        self.descr = None
        self.local_refs = 0
        self.worker_refs = 0
        self.pins = 0
        self.futures: List[Future] = []
        self.waiters: List[Callable] = []  # called with (oid,) on completion
        self.task_id = task_id
        self.value = None
        self.has_value = False
        self.segment = None
        # WorkerHandle whose process created this object's shm segment (None
        # when the driver did).  Frees are routed back to the creator so its
        # store can pool the pages for in-place reuse.
        self.creator = None
        # True once this object's descriptor left the process (a worker may
        # hold zero-copy views over the segment) or was mapped locally —
        # such segments must not be pooled for in-place reuse.
        self.shipped = False
        # ObjectIDs (binary) of refs pickled inside this object's value;
        # pinned until this object is freed.
        self.nested_ids: List[bytes] = []
        # WorkerHandle that exported this entry as a PENDING shell and
        # owes an export_complete; its death fails the object (owner
        # death semantics, reference: OwnerDiedError).
        self.exporter = None

    def refcount(self):
        return self.local_refs + self.worker_refs + self.pins


class TaskRecord:
    __slots__ = (
        "spec", "requirements", "deps_pending", "retries_left", "node",
        "worker", "dispatched", "cancelled", "is_actor_creation", "actor_id",
        "pg_id", "bundle_index", "sched_key", "locality_homes",
        "app_retries_left",
    )

    def __init__(self, spec, requirements, retries_left):
        self.spec = spec
        self.requirements = requirements
        self.deps_pending = 0
        # Two independent budgets, both seeded from max_retries:
        # retries_left pays for SYSTEM failures (worker/node death —
        # decremented in the death paths), app_retries_left for the
        # retry_exceptions= opt-in application-error retries.  An app
        # error must never burn a system-retry slot (and vice versa) —
        # pinned by the retry-counting test.
        self.retries_left = retries_left
        self.app_retries_left = retries_left
        self.node = None
        self.worker = None
        self.dispatched = False
        self.cancelled = False
        self.is_actor_creation = False
        self.actor_id: Optional[bytes] = None
        self.pg_id: Optional[PlacementGroupID] = None
        self.bundle_index: Optional[int] = None
        # Scheduling-class tuple, computed once at first enqueue (the
        # spec's strategy/env/requirements never change afterwards) so
        # re-enqueues, cancels and dispatch scans are dict ops only.
        # Locality NEVER folds into this key — it would shatter lease
        # reuse; locality is resolved at pick time per record.
        self.sched_key: Optional[tuple] = None
        # Lazily-scanned {store_id: argument bytes homed there} for
        # locality-aware placement; scanned once at first pick (deps are
        # READY by then, so descriptors are known and pinned).
        self.locality_homes: Optional[Dict[str, int]] = None


ALIVE, RESTARTING, DEAD = "ALIVE", "RESTARTING", "DEAD"


def _apply_strategy(rec: "TaskRecord", spec: dict):
    strategy = spec.get("scheduling_strategy")
    if strategy and strategy[0] == "placement_group":
        rec.pg_id = strategy[1]
        rec.bundle_index = strategy[2]


class ActorState:
    """FSM mirrors the reference's GcsActorManager diagram
    (src/ray/gcs/gcs_server/gcs_actor_manager.h:243-281):
    PENDING_CREATION -> ALIVE -> (RESTARTING ->)* DEAD."""

    __slots__ = (
        "actor_id", "name", "namespace", "cls_payload", "func_id",
        "init_args", "init_kwargs", "options", "worker", "node", "status",
        "restarts_left", "queue", "inflight", "created_future",
        "death_cause", "handle_count", "max_concurrency", "checkpoint",
    )

    def __init__(self, actor_id):
        self.actor_id = actor_id
        self.name = None
        self.namespace = "default"
        self.cls_payload = None
        self.func_id = None
        self.init_args = None
        self.init_kwargs = None
        self.options = {}
        self.worker = None
        self.node = None
        self.status = "PENDING"
        self.restarts_left = 0
        self.queue: deque = deque()  # TaskRecords not yet dispatched
        self.inflight: Dict[bytes, TaskRecord] = {}
        self.created_future = Future()
        self.death_cause = None
        self.handle_count = 0
        self.max_concurrency = 1
        # Latest __ray_save__ state descriptor (restartable actors): the
        # restart's create_actor carries it so __ray_restore__ can run.
        self.checkpoint = None


class WorkerHandle:
    __slots__ = (
        "worker_id", "conn", "proc", "node", "send_lock", "env_key",
        "inflight", "actor_id", "tpu_chips", "idle_since", "released",
        "ready", "dead", "outbox", "outbuf", "spawned_at",
        "lease_key", "lease_req", "lease_pg", "blocked",
        "pending_force_kill", "direct_addr", "client_lease",
        "oom_killed", "last_dispatch_ts", "lease_expiry",
        "lease_offer_ts", "lease_caps", "last_seen", "hc_suspect",
        "hc_misses", "hc_probe_ts",
    )

    def __init__(self, worker_id, conn, proc, node, env_key, tpu_chips):
        self.worker_id = worker_id
        self.conn = conn  # None until the worker dials back (accept thread)
        self.proc = proc  # subprocess.Popen
        self.node = node
        self.send_lock = threading.Lock()  # lock-order: io-guard
        self.env_key = env_key
        # Tasks pushed to this worker and not yet resulted, in send order
        # (the worker executes its queue FIFO).  Reference: task pipelining
        # onto leased workers, direct_task_transport.h:75.
        self.inflight: Dict[bytes, TaskRecord] = {}
        self.actor_id: Optional[bytes] = None
        self.tpu_chips = tpu_chips or []
        self.idle_since = time.monotonic()
        self.released = False  # resources released while blocked in get
        self.blocked = False    # inside ray.get: no new pipelined tasks
        self.ready = threading.Event()
        self.dead = False
        self.outbox: List[tuple] = []
        self.outbuf: List[tuple] = []  # conflation-sender batch buffer
        self.spawned_at = time.monotonic()
        # Lease state: while leased, the worker holds lease_req resources on
        # its node (or lease_pg's bundle) and serves one scheduling class.
        self.lease_key: Optional[tuple] = None
        self.lease_req: Optional[Dict[str, float]] = None
        self.lease_pg: Optional[tuple] = None  # (pg_id, bundle_index)
        # Set by force-cancel: victim task id; the proc is terminated only
        # after a steal pass rescues the other pipelined tasks.
        self.pending_force_kill: Optional[bytes] = None
        # Direct-push endpoint (reported in the worker's "ready") and, when
        # leased to a peer caller, that caller's WorkerHandle (the head
        # only does resource accounting for such leases; tasks/results
        # bypass it entirely — direct_task_transport.cc:568).
        self.direct_addr = None
        self.client_lease: Optional["WorkerHandle"] = None
        # Memory-monitor bookkeeping: oom_killed types the death error;
        # last_dispatch_ts picks the NEWEST task's worker as the victim.
        self.oom_killed = False
        self.last_dispatch_ts = 0.0
        # Decentralized dispatch: while client-leased, the holder must
        # renew before this monotonic deadline or the reaper revokes the
        # lease (None = no TTL: legacy holder or TTL disabled).  On a
        # LESSEE handle, lease_offer_ts holds per-scheduling-class
        # [last_offer_ts, eligible_specs_accumulated] pairs that
        # rate-limit and threshold unsolicited bulk grants.
        self.lease_expiry: Optional[float] = None
        self.lease_offer_ts: Dict[tuple, list] = {}
        # Capability gate for UNSOLICITED lease grants (PR-3 convention:
        # never send a new verb to a peer that would silently drop it —
        # here the drop would leak the acquired leases).  True for
        # workers this head spawned (same build, env-matched); an
        # external client earns it by sending a v1 lease_req.
        self.lease_caps = False
        # Failure detection: last message seen from this worker
        # (stamped by the reader wrapper, re-seeded with the initial
        # delay at attach) + the suspicion machine's state.
        self.last_seen = time.monotonic()
        self.hc_suspect = False
        self.hc_misses = 0
        self.hc_probe_ts = 0.0

    def send(self, msg):
        with self.send_lock:
            if self.conn is None:
                self.outbox.append(msg)
            else:
                protocol.send(self.conn, msg)

    def queue_msg(self, msg):
        """Buffer a task-path message for the conflation sender: while
        one flush's pickle+write syscall runs, later dispatches pile into
        the next batch — self-clocking batching with no added latency
        floor (reference: gRPC stream write coalescing)."""
        with self.send_lock:
            self.outbuf.append(msg)

    def flush_buffered(self):
        with self.send_lock:
            if not self.outbuf:
                return
            msgs, self.outbuf = self.outbuf, []
            payload = protocol.make_batch(msgs)
            if self.conn is None:
                self.outbox.append(payload)
            else:
                try:
                    protocol.send(self.conn, payload)
                except BaseException:
                    # Failed delivery is how worker death is usually
                    # discovered: put the batch back (send_lock is held,
                    # so order is preserved) so the death path can
                    # reroute buffered free_segment messages to their
                    # store-side fallback instead of leaking segments.
                    self.outbuf[:0] = msgs
                    raise

    def attach(self, conn):
        with self.send_lock:
            self.conn = conn
            for msg in self.outbox:
                protocol.send(conn, msg)  # noqa: RTL604 -- re-register attaches under the lock by design: the ack must beat any locked send onto this conn; outbox is bounded by the blip window
            self.outbox.clear()


class AgentHandle:
    """Head-side proxy for one node agent daemon (reference: the GCS's
    per-raylet NodeManager client, gcs_node_manager.h:41)."""

    def __init__(self, conn, store_id: str, shm_dir: str, info: dict):
        self.conn = conn
        self.store_id = store_id
        self.shm_dir = shm_dir
        self.info = info
        self.send_lock = threading.Lock()  # lock-order: io-guard
        self.node: Optional["NodeState"] = None
        self.dead = False
        self._rid = 0
        self._pending: Dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        # Failure detection: last message from this agent (heartbeats
        # are the floor) + suspicion state (SUSPECT -> probe -> DEAD).
        self.last_seen = time.monotonic()
        self.hc_suspect = False
        self.hc_misses = 0
        self.hc_probe_ts = 0.0

    def send(self, msg):
        with self.send_lock:
            protocol.send(self.conn, msg)

    def request_segment(self, name: str, timeout: float = 30.0):
        """Blocking HEAD-RELAYED read of a remote segment's serialized
        parts — the fallback when a direct object-server pull is not
        possible.  Must be called WITHOUT the runtime lock held.  The
        deadline makes a stalled agent a structured, reconstructable
        loss (phase="stalled") instead of a 30s-or-forever hang."""
        with self._pending_lock:
            self._rid += 1
            rid = self._rid
            fut = self._pending[rid] = Future()
        self.send(("read_segment", rid, name))
        try:
            ok, payload = fut.result(timeout=timeout)
        except Exception as e:  # concurrent.futures.TimeoutError
            with self._pending_lock:
                self._pending.pop(rid, None)
            protocol.note_net_event("stall_timeouts")
            raise exc.ObjectLostError(
                f"relay read of {name} from {self.store_id} stalled "
                f"past {timeout}s",
                object_id=_seg_oid_hex(name), home=self.store_id,
                phase="stalled") from e
        if not ok:
            raise exc.ObjectLostError(object_id=_seg_oid_hex(name),
                                      home=self.store_id, phase="relay")
        return payload  # (meta, [bytes...])

    def deliver(self, rid, ok, payload):
        with self._pending_lock:
            fut = self._pending.pop(rid, None)
        if fut is not None:
            fut.set_result((ok, payload))

    def fail_all(self, err):
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_result((False, repr(err)))


class NodeState:
    """One schedulable node.  In-process multi-node (the cluster_utils.Cluster
    pattern, reference python/ray/cluster_utils.py:99) gives several NodeStates
    on one host — the scheduler can't tell the difference, which is exactly
    how the reference tests multi-node logic on one machine."""

    __slots__ = (
        "node_id", "resources", "available", "labels", "idle_workers",
        "all_workers", "tpu_free", "alive", "agent", "store_id",
        "draining",
    )

    def __init__(self, node_id, resources, labels=None, agent=None,
                 store_id=""):
        self.node_id = node_id
        self.resources = dict(resources)
        self.available = dict(resources)
        self.labels = labels or {}
        self.idle_workers: Dict[str, List[WorkerHandle]] = {}
        self.all_workers: Dict[int, WorkerHandle] = {}
        self.tpu_free: List[int] = list(range(int(resources.get("TPU", 0))))
        self.alive = True
        # Out-of-process nodes (real multi-host) have a per-node agent
        # daemon (the raylet analog, _private/node_agent.py) and their own
        # object store; in-process test nodes share the head's store.
        self.agent: Optional["AgentHandle"] = agent
        self.store_id = store_id
        # Drain in progress (elastic pods): the node is on its way out —
        # no new placements of any kind (can_fit refuses), existing work
        # finishes or is stolen/revoked (reference: the raylet's drain
        # state under the GCS DrainNode RPC).
        self.draining = False

    def can_fit(self, req: Dict[str, float]) -> bool:
        if self.draining:
            return False
        return all(self.available.get(k, 0.0) >= v - 1e-9
                   for k, v in req.items())

    def feasible(self, req: Dict[str, float]) -> bool:
        return all(self.resources.get(k, 0.0) >= v - 1e-9
                   for k, v in req.items())

    def acquire(self, req: Dict[str, float]):
        for k, v in req.items():
            self.available[k] = self.available.get(k, 0.0) - v

    def release(self, req: Dict[str, float]):
        for k, v in req.items():
            self.available[k] = self.available.get(k, 0.0) + v


class PlacementGroupState:
    __slots__ = ("pg_id", "bundles", "strategy", "name", "reserved",
                 "created_future", "removed", "used")

    def __init__(self, pg_id, bundles, strategy, name):
        self.pg_id = pg_id
        self.bundles = bundles  # list of resource dicts
        self.strategy = strategy
        self.name = name
        self.reserved: List[Optional[NodeID]] = [None] * len(bundles)
        self.created_future = Future()
        self.removed = False
        # Per-bundle resources currently consumed by running tasks/actors —
        # the shadow-resource accounting of the reference
        # (placement_group_resource_manager.cc CPU_group_<pgid> resources).
        self.used: List[Dict[str, float]] = [dict() for _ in bundles]


def worker_send_safe(worker: "WorkerHandle", msg):
    try:
        worker.send(msg)
    except Exception:
        pass  # requester died; its death path cleans up


# Every loss error carries the structured object_id field even at sites
# that only see the segment (one naming-rule implementation, recovery.py).
_seg_oid_hex = recovery.seg_oid_hex


class Runtime:
    """The driver's runtime.  Public API (api.py) and ObjectRef route here."""

    def __init__(self, config: Config, num_cpus=None, num_tpus=None,
                 resources=None, job_name="default"):
        self.config = config
        # Failover restore peek: the snapshot is read EARLY (before the
        # store/listeners exist) because a restarted head must ADOPT the
        # dead head's session id — shm segment names are
        # ``rtpu-<session>-<oid>`` and the worker rendezvous socket dir
        # is keyed by session, so a fresh session id would orphan every
        # surviving segment and strand reconnecting head-local workers.
        self._restore_data = None
        if config.gcs_restore and config.gcs_snapshot_path \
                and os.path.exists(config.gcs_snapshot_path):
            self._restore_data = self._load_snapshot(
                config.gcs_snapshot_path)
        self.session_id = ((self._restore_data or {}).get("session_id")
                          or os.urandom(4).hex())
        self.job_id = JobID.from_random()
        self.job_name = job_name
        self.lock = threading.RLock()
        self._tls = threading.local()
        self.shm = ShmStore(config.shm_dir, config.object_store_memory,
                            self.session_id,
                            pool_bytes=config.shm_pool_bytes)

        self.objects: Dict[ObjectID, ObjectState] = {}
        self.tasks: Dict[bytes, TaskRecord] = {}
        self.actors: Dict[bytes, ActorState] = {}
        self.named_actors: Dict[Tuple[str, str], bytes] = {}
        self.placement_groups: Dict[bytes, PlacementGroupState] = {}
        self.pending_pgs: deque = deque()
        self.kv: Dict[str, Dict[bytes, bytes]] = {}
        self.nodes: Dict[NodeID, NodeState] = {}
        self.node_order: List[NodeID] = []
        # Resource-waiting TaskRecords, bucketed by scheduling class
        # (resource shape + strategy) so dispatch is O(#classes), not
        # O(#queued): scanning a class stops at its first unplaceable
        # head — same-shaped tasks behind it cannot place either.
        # (Reference: per-SchedulingKey lease queues in
        # direct_task_transport.h:75 / scheduling classes.)
        self.pending_tasks: Dict[tuple, deque] = {}
        # Workers currently holding a lease, by scheduling class — the
        # pipelining pool (reference: the submitter's per-SchedulingKey
        # worker leases, direct_task_transport.h:75).
        self.leased_workers: Dict[tuple, List[WorkerHandle]] = {}
        # Lineage: creating-task spec kept while any of its return objects
        # is alive, so a lost object can be rebuilt by re-execution
        # (reference: object_recovery_manager.h:41, task_manager.h:174
        # lineage pinning).  BOUNDED by lineage_bytes_budget — entries
        # evict oldest-first past it and recovery then refuses (its own
        # leaf _lock is pinned in tests/test_lockcheck.py).
        self.lineage = recovery.LineageTable(config.lineage_bytes_budget)
        self.functions: Dict[str, bytes] = {}
        self.worker_funcs: Dict[int, set] = {}  # conn fileno -> func_ids sent
        self.task_events: deque = deque(maxlen=200_000)
        self.events: Dict[str, deque] = {}  # topic -> payload bytes
        self._conn_to_worker: Dict[Any, WorkerHandle] = {}
        self._conn_to_agent: Dict[Any, AgentHandle] = {}
        self._agents: Dict[str, AgentHandle] = {}  # store_id -> handle
        self._pending_workers: Dict[str, WorkerHandle] = {}
        self._workers_by_hex: Dict[str, WorkerHandle] = {}
        # Direct chunked pulls from remote object servers (reference:
        # ObjectManager::Pull); the head-relay path remains as fallback
        # and counts its uses (tests assert it stays cold).
        self._puller = object_transfer.ObjectPuller(  # authkey set below
            b"", pool_size=config.object_pool_size,
            stripe_threshold=config.object_stripe_threshold,
            # Explicit net params: the head's _system_config overrides
            # must govern its own pulls, not the env-built
            # GLOBAL_CONFIG.
            net_config=object_transfer.net_params(config))
        self.relayed_segments = 0   # head-relayed agent reads (fallback)
        self.brokered_parts = 0     # worker getparts served via the head
        # Write-direction counters (all zero while direct_puts is off —
        # pinned by tests): direct_puts/direct_put_bytes = values that
        # reached this store over the data plane (the head saw only the
        # O(1) put_commit message); brokered_put_parts = legacy
        # whole-value put_parts messages assembled here while the direct
        # path was ON (old-verb clients, push failures, and mid-size
        # puts under the client's direct-put floor — a few MB, where
        # the fire-and-forget message beats three round trips).
        self.direct_puts = 0
        self.direct_put_bytes = 0
        self.brokered_put_parts = 0
        # Legacy put_parts assemblies run off the reader threads but
        # BOUNDED: past this many in flight the reader blocks before
        # spawning (TCP backpressure then throttles the bursting
        # client), so a legacy-put storm cannot pin unbounded buffer
        # memory in concurrent multi-hundred-MB memcpys.
        self._put_assembly_sem = threading.BoundedSemaphore(4)
        # Locality-aware placement counters (tentpole observability):
        # hits = tasks placed on their top-locality node, misses = a
        # preference existed but that node couldn't take the task,
        # bytes_saved = argument bytes that did NOT cross the network
        # because of a locality placement.
        self.locality_hits = 0
        self.locality_misses = 0
        self.locality_bytes_saved = 0
        # Worker-side data-plane counters, aggregated from periodic
        # ("xfer_stats", {...}) deltas: singleflight pull dedup and the
        # argument prefetcher's hit/waste bytes.
        self.deduped_pulls = 0
        self.prefetch_hit_bytes = 0
        self.prefetch_waste_bytes = 0
        # Decentralized-dispatch counters (all zero when the
        # decentralized_dispatch switch is off — pinned by tests):
        # lease_grants     = worker leases handed to peer holders
        #                    (solicited lease_req + unsolicited bulk
        #                    grants piggybacked on submit bursts),
        # lease_revocations= leases the head revoked (node/worker death,
        #                    TTL expiry),
        # head_brokered_submits = specs that reached the head's scheduler
        #                    over the wire (the path leases exist to
        #                    drain),
        # leased_submits / spillbacks = holder-side counters aggregated
        #                    from the periodic xfer_stats deltas.
        self.lease_grants = 0
        self.lease_revocations = 0
        self.head_brokered_submits = 0
        self.leased_submits = 0
        self.spillbacks = 0
        # Recovery counters (all zero while config.recovery is off —
        # pinned by tests): reconstructions = lost objects whose
        # producer was re-queued from lineage (head-side, plus
        # worker-side deltas via xfer_stats); reconstruction_failures =
        # losses recovery could not cover (no/evicted lineage, depleted
        # retries, non-reconstructable types); actor_restarts = actor
        # respawns after worker/node death; chaos_kills = faults the
        # chaos harness injected (ray_tpu.chaos).
        self.reconstructions = 0
        self.reconstruction_failures = 0
        self.actor_restarts = 0
        self.chaos_kills = 0
        # Head-failover counters (all zero while head_failover is off or
        # no restart happened — pinned by tests): gcs_snapshots /
        # gcs_snapshot_failures count the persistence loop's writes;
        # reconnected_nodes = agents that re-dialed and re-claimed their
        # restored node; reregistered_workers = surviving worker/client
        # processes that re-registered across a head restart;
        # adopted_actors = restored actor incarnations re-claimed by
        # their surviving worker (state intact, no __init__ re-run).
        self.gcs_snapshots = 0
        self.gcs_snapshot_failures = 0
        self.reconnected_nodes = 0
        self.reregistered_workers = 0
        self.adopted_actors = 0
        # Elastic-pod counters (all zero while elastic_drain is off —
        # pinned by tests): preemptions = preempt_notice messages
        # received from agents (spot warning windows); drains_completed /
        # drain_timeouts = drain_node() outcomes (a timeout falls
        # through to hard-kill recovery); objects_migrated = sole-copy
        # objects pulled off a draining node and re-homed on the head's
        # surviving store; autoscaler_errors lives autoscaler-side
        # (StandardAutoscaler.stats()) next to these.
        self.preemptions = 0
        self.drains_completed = 0
        self.drain_timeouts = 0
        self.objects_migrated = 0
        # Failure-detection counters (all zero while failure_detection
        # is off — pinned by tests): suspected_nodes = peers (node
        # agents AND workers) the suspicion machine marked SUSPECT
        # after health_check_timeout_s of silence; stall_timeouts /
        # net_retries / hedged_fetches aggregate the deadline core's
        # process-wide counters from every worker/client (xfer_stats
        # deltas) plus this head process's own (merged at
        # transfer_stats time).
        self.suspected_nodes = 0
        self.stall_timeouts = 0
        self.net_retries = 0
        self.hedged_fetches = 0
        # Push-shuffle counters (all zero while push_shuffle is off —
        # pinned by tests): shuffle_pushed_bytes = partition bytes map
        # tasks pushed straight into reducer-node stores (never through
        # the head), shuffle_merges = k-way merge passes reducers ran
        # on arrival, shuffle_spills = partitions reserve_put degraded
        # to spill files under store pressure, shuffle_hedges = pushes
        # re-routed through a healthy store after a stalled/dead link
        # (worker deltas via xfer_stats, plus the driver coordinator's
        # own — merged at transfer_stats time).
        self.shuffle_pushed_bytes = 0
        self.shuffle_merges = 0
        self.shuffle_spills = 0
        self.shuffle_hedges = 0
        # Distributed-training counters (all zero while
        # distributed_training is off — pinned by tests):
        # microbatch_pushes = micro-batch activation/grad segments
        # pipeline stage actors pushed straight into their neighbor
        # stage's store (never through the head), stage_restarts =
        # pipeline stage actors restored from a __ray_save__ checkpoint
        # after a death, learner_queue_stalls = IMPALA learner waits on
        # an empty host->device batch queue (worker deltas via
        # xfer_stats, plus the driver-process trainer's own — merged at
        # transfer_stats time).
        self.microbatch_pushes = 0
        self.stage_restarts = 0
        self.learner_queue_stalls = 0
        # Drain rendezvous: aid -> Event set when the forced
        # ("checkpoint_now", aid) round-trips as an actor_checkpoint;
        # node_id -> [done_event, outcome, deadline_abs] for that
        # node's in-flight drain (a second drain_node call — scale-down
        # racing a preemption notice — waits the FIRST drain out past
        # its own deadline and returns its real outcome, instead of
        # failing into a hard kill mid-drain or mislabeling a timeout
        # as success).
        self._drain_ck_events: Dict[bytes, threading.Event] = {}
        self._node_drains: Dict[NodeID, list] = {}
        # Driver-side pubsub listeners: topic -> callbacks fired (outside
        # the lock) when a worker "event" lands — the serve controller's
        # scale events wake the autoscaler loop through this.
        self._event_listeners: Dict[str, List] = {}
        # Reconcile state for a restarted head: restored-but-unclaimed
        # nodes/actors/leases wait until _failover_grace_until for their
        # surviving owners to re-register; the grace timer then revokes
        # or re-creates the remainder.  _grace_objects tracks object ids
        # a blip-window mget implicitly created (unknown to the restored
        # tables) — still PENDING at the deadline, they fail as
        # reconstruction candidates instead of waiting forever.
        self._awaiting_nodes: Dict[str, NodeState] = {}  # store_id -> node
        self._restored_actors: Dict[bytes, dict] = {}    # aid -> info
        self._restored_leases: List[tuple] = []
        self._pending_lease_claims: Dict[str, tuple] = {}
        self._grace_objects: set = set()
        self._failover_grace_until = 0.0
        # Identity of this process's object store: SHM descriptors carry it
        # so consumers know whether a segment is locally attachable or must
        # be shipped (reference: owner-based object directory).  A
        # restarted head adopts the dead head's store id too — restored
        # descriptors homed "at the head" must keep resolving here.
        self.store_id = ((self._restore_data or {}).get("store_id")
                         or os.urandom(8).hex())
        self.spill_dir = (config.spill_dir
                          or f"/tmp/ray_tpu_spill_{self.session_id}")
        # Direct-put reservations degrade to the spill path (instead of
        # overcommitting tmpfs) through the store's spill_dir.
        self.shm.spill_dir = self.spill_dir
        self._stopped = False
        self._extra_workers = 0
        # Connection admission gate: the accept loops start mid-__init__
        # but a RESTARTED head must not serve agent_ready / reregister
        # until the snapshot restore populated the tables — an early
        # reregister would be nacked (node not restored yet) and the
        # surviving worker would exit instead of being adopted.
        self._boot_ready = threading.Event()

        # Worker rendezvous: workers are plain subprocesses running
        # ``python -m ray_tpu._private.worker_main`` that dial back over a
        # unix socket (reference: raylet spawns default_worker.py which
        # connects back over the raylet socket, services.py:1346).
        self._sock_dir = f"/tmp/ray_tpu_{self.session_id}"
        os.makedirs(self._sock_dir, exist_ok=True)
        self._authkey = (bytes.fromhex(config.authkey_hex)
                         if config.authkey_hex else os.urandom(16))
        self._puller._authkey = self._authkey
        sock_path = os.path.join(self._sock_dir, "worker.sock")
        try:
            # An adopted session leaves the dead head's socket file
            # behind; AF_UNIX bind fails on an existing path.
            os.unlink(sock_path)
        except OSError:
            pass
        self._listener = multiprocessing.connection.Listener(
            sock_path, "AF_UNIX", backlog=512, authkey=self._authkey)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, args=(self._listener,), daemon=True,
            name="ray_tpu-accept")
        self._accept_thread.start()
        # TCP listener: node agents and their workers dial in here
        # (reference: the GCS + raylet gRPC ports).  Head-host-local
        # workers keep the unix socket.
        self._tcp_listener = multiprocessing.connection.Listener(
            (config.listen_host, config.listen_port), "AF_INET",
            backlog=512, authkey=self._authkey)
        self.tcp_address = protocol.format_address(
            self._tcp_listener.address)
        self._tcp_accept_thread = threading.Thread(
            target=self._accept_loop, args=(self._tcp_listener,),
            daemon=True, name="ray_tpu-accept-tcp")
        self._tcp_accept_thread.start()
        # HEAD OBJECT SERVER: direct chunked pulls from the head node's
        # own store (driver puts, head-local worker results).  Keeps the
        # head's control-plane connections out of the payload path — a
        # remote consumer of a head-homed segment dials here instead of
        # round-tripping a multi-hundred-MB getparts reply through the
        # worker-message handler (reference: every node's object manager
        # has a transfer port, object_manager.h:117 — the head included).
        self._obj_listener = multiprocessing.connection.Listener(
            (config.listen_host, 0), "AF_INET", backlog=64,
            authkey=self._authkey)
        obj_adv = config.object_advertise_host or config.listen_host
        if obj_adv == "0.0.0.0":
            import socket as _socket

            obj_adv = _socket.gethostbyname(_socket.gethostname())
        self.object_addr = protocol.format_address(
            (obj_adv, self._obj_listener.address[1]))
        threading.Thread(target=self._object_server_loop, daemon=True,
                         name="ray_tpu-objsrv").start()

        head_resources = {"CPU": float(num_cpus if num_cpus is not None
                                       else os.cpu_count() or 1)}
        if num_tpus:
            head_resources["TPU"] = float(num_tpus)
        if resources:
            head_resources.update(resources)
        head_resources.setdefault("memory", float(2 ** 33))
        restored_head_id = (self._restore_data or {}).get("head_node_id")
        self.head_node = self._add_node_locked(
            head_resources, labels={"head": "1"},
            node_id=(NodeID(bytes.fromhex(restored_head_id))
                     if restored_head_id else None))

        self._reaper = threading.Thread(
            target=self._reap_loop, daemon=True, name="ray_tpu-reaper")
        self._reaper.start()
        if config.failure_detection:
            # Heartbeat suspicion (reference: GcsHealthCheckManager):
            # silence -> SUSPECT -> probe -> DEAD, feeding the existing
            # node/worker-death paths — a stalled peer becomes
            # indistinguishable from a killed one within one suspicion
            # window.  Off-switch = no thread, no probes, counter zero.
            threading.Thread(target=self._suspicion_loop, daemon=True,
                             name="ray_tpu-suspicion").start()
        if config.memory_monitor_threshold > 0:
            threading.Thread(target=self._memory_monitor_loop,
                             daemon=True, name="ray_tpu-memmon").start()
        # Worker log rings (worker_id_hex -> recent lines) + the tailer
        # that feeds them and re-prints to the driver (log_monitor.py).
        self._worker_logs: Dict[str, deque] = {}
        threading.Thread(target=self._log_monitor_loop, daemon=True,
                         name="ray_tpu-logmon").start()
        # Conflation sender: dispatches buffer task-path messages (exec/
        # func/obj/mgot/free_segment/reply) per worker; this thread
        # flushes them as ("batch", ...) frames.  While one flush's
        # pickle+write runs, later dispatches coalesce into the next
        # batch — a burst of .remote() calls costs ~1 syscall per batch
        # instead of one per task.  The dirty set has its own leaf lock
        # so reply paths running off the IO threads don't contend on (or
        # need) the big runtime lock just to mark a worker dirty.
        self._sender_event = threading.Event()
        self._dirty_workers: set = set()
        self._dirty_agent_msgs: List[tuple] = []
        self._dirty_lock = threading.Lock()
        # Client lease requests waiting for capacity (reference: the
        # raylet's queued RequestWorkerLease); serviced by _dispatch_locked
        # on every resource release, expired by a per-request timer.
        self._pending_client_leases: deque = deque()
        # Actor-handle transfer tokens (actor.py __reduce__): token ->
        # actor_id for unconsumed pickled-handle counts; the consumed set
        # absorbs cross-connection create/consume reordering (bounded —
        # eviction of a real early consume merely leaves the actor's
        # count conservatively high).
        self._actor_tokens: Dict[bytes, bytes] = {}
        self._actor_tokens_consumed: set = set()
        # Task execution spans (worker "spans" batches) + per-message-
        # handler latency stats (reference: task events + event_stats.h).
        self.task_spans: deque = deque(maxlen=200_000)
        self._handler_stats: Dict[str, list] = {}
        self._handler_stats_lock = threading.Lock()
        self._sender = threading.Thread(
            target=self._task_sender_loop, daemon=True,
            name="ray_tpu-sender")
        self._sender.start()
        # Sharded dispatch (decentralized_dispatch on): the hot submit
        # and reply paths no longer run the global dispatch scan inside
        # their own lock hold — they mark the affected scheduling
        # class(es) dirty (per-shard dirty set, own LEAF lock: never
        # taken around another lock; the event is set outside it) and
        # the dispatcher thread drains dirty shards, each pass scoped to
        # its class instead of scanning every queue.  With the switch
        # off the shards are never marked and every site dispatches
        # inline exactly as before.
        self._dispatch_dirty: set = set()
        self._dispatch_dirty_lock = threading.Lock()  # lock-order: leaf
        self._dispatch_event = threading.Event()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="ray_tpu-dispatch")
        self._dispatcher.start()
        # GCS-analog persistence: mutators bump _gcs_dirty; the snapshot
        # thread writes when it changed (reference: GCS tables persisted
        # to redis, redis_store_client.h:28).  Restore runs after the
        # dispatch machinery is up — it re-creates named actors.
        self._gcs_dirty = 0
        self._gcs_snapshotted = 0
        self._gcs_stop = threading.Event()
        # Serializes snapshot writes: shutdown()'s final clean snapshot
        # must not interleave with an in-flight periodic write (both use
        # the same pid-keyed tmp file — concurrent writers would tear
        # it, and a stale periodic os.replace landing AFTER the clean
        # one would un-mark the shutdown).
        self._gcs_write_lock = threading.Lock()  # lock-order: io-guard
        # Object-row cache for huge tables (see _snapshot_gcs).
        self._snap_obj_cache = None
        if self._restore_data is not None:
            self._apply_restore(self._restore_data)
            self._restore_data = None
        if config.gcs_snapshot_path:
            threading.Thread(target=self._gcs_snapshot_loop, daemon=True,
                             name="ray_tpu-gcs-snap").start()
        self._boot_ready.set()  # admission gate open: tables restored
        atexit.register(self.shutdown)

    def _task_sender_loop(self):
        while not self._stopped:
            self._sender_event.wait()
            self._sender_event.clear()
            with self._dirty_lock:
                dirty, self._dirty_workers = self._dirty_workers, set()
                agent_msgs, self._dirty_agent_msgs = (
                    self._dirty_agent_msgs, [])
            for w in dirty:
                try:
                    w.flush_buffered()
                except Exception:
                    self._on_worker_death(w)
            for agent, msg in agent_msgs:
                if agent.dead:
                    continue
                try:
                    agent.send(msg)
                except Exception:
                    pass  # best-effort, same as the old inline send

    def _mark_dirty(self, worker: "WorkerHandle"):
        with self._dirty_lock:
            self._dirty_workers.add(worker)
        self._sender_event.set()

    def _queue_agent_send(self, agent: "AgentHandle", msg: tuple):
        """Fire-and-forget agent control frame (segment unlinks),
        deferred to the sender thread: the free path runs under the
        runtime lock, and a blocking send there stalls every other
        acquirer on one slow agent conn (lockgraph RTL604)."""
        with self._dirty_lock:
            self._dirty_agent_msgs.append((agent, msg))
        self._sender_event.set()

    # Sentinel marking "every shard needs a pass" (resources freed).
    _DIRTY_ALL = object()

    def _dispatch_loop(self):
        """Drain dirty dispatch shards.  Runs the same per-class pass the
        inline path runs, but OFF the submitting/replying thread: while
        this thread scans one class under the runtime lock, the next
        submit burst's registration only pays its table writes."""
        while not self._stopped:
            self._dispatch_event.wait()
            self._dispatch_event.clear()
            with self._dispatch_dirty_lock:
                dirty, self._dispatch_dirty = self._dispatch_dirty, set()
            if not dirty or self._stopped:
                continue
            keys = (None if self._DIRTY_ALL in dirty
                    else [k for k in dirty])
            try:
                with self.lock:
                    self._dispatch_locked(keys)
            except Exception:
                import traceback
                traceback.print_exc()

    def _request_dispatch_locked(self, keys=None):
        """Dispatch trigger for the hot paths.  decentralized_dispatch
        off: inline full pass, byte-identical to the pre-shard behavior.
        On: mark the affected shard(s) dirty (``keys`` None = all — a
        resource was freed, anything may now place) and let the
        dispatcher thread run the scan outside this caller's lock
        hold."""
        if not self.config.decentralized_dispatch:
            self._dispatch_locked()
            return
        with self._dispatch_dirty_lock:
            if keys is None:
                self._dispatch_dirty.add(self._DIRTY_ALL)
            else:
                self._dispatch_dirty.update(keys)
        self._dispatch_event.set()

    def _queue_send(self, worker: "WorkerHandle", msg: tuple):
        """Buffer ``msg`` for the conflation sender.  Back-to-back sends
        to one worker (a burst of mgot/obj replies, frees, execs) leave
        as one ("batch", ...) pickle + one write."""
        worker.queue_msg(msg)
        self._mark_dirty(worker)

    # ------------------------------------------------------------- nodes --
    def _add_node_locked(self, resources, labels=None, agent=None,
                         store_id=None, node_id=None) -> NodeState:
        # node_id override: a restarted head re-creates restored nodes
        # (its own included) under their OLD ids, so surviving workers'
        # RAY_TPU_NODE_ID and node-affinity strategies stay valid.
        node = NodeState(node_id or NodeID.from_random(), resources,
                         labels, agent=agent,
                         store_id=(self.store_id if store_id is None
                                   else store_id))
        self.nodes[node.node_id] = node
        self.node_order.append(node.node_id)
        return node

    def add_node(self, num_cpus=1.0, num_tpus=0.0, resources=None,
                 labels=None) -> NodeID:
        """Add a simulated cluster node (reference:
        python/ray/cluster_utils.py:165 Cluster.add_node)."""
        r = {"CPU": float(num_cpus)}
        if num_tpus:
            r["TPU"] = float(num_tpus)
        if resources:
            r.update(resources)
        r.setdefault("memory", float(2 ** 33))
        with self.lock:
            node = self._add_node_locked(r, labels)
            self._dispatch_locked()
            return node.node_id

    def remove_node(self, node_id: NodeID):
        """Kill a node and everything on it (chaos-testing hook; reference:
        test_utils.py kill_raylet / NodeKillerActor)."""
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return
            node.alive = False
            workers = list(node.all_workers.values())
            agent = node.agent
        if agent is not None and not agent.dead:
            # Out-of-process node: the agent terminates its workers and
            # exits; conn EOFs drive the death handling.
            try:
                agent.send(("shutdown",))
            except Exception:
                pass
            try:
                agent.conn.close()
            except Exception:
                pass
            self._on_agent_death(agent)
        for w in workers:
            try:
                w.proc.terminate()
            except Exception:
                pass
        # Death handling proceeds via conn EOF in the IO loop.

    # ------------------------------------------------------ elastic drain --
    def drain_node(self, node_id, deadline_s=None,
                   reason: str = "scale_down") -> bool:
        """Graceful, deadline-bounded node removal — the DrainNode
        protocol (reference: gcs_node_manager DrainNode RPC + the
        raylet's drain state).  Within the deadline: (1) stop new
        placements (``node.draining`` — can_fit refuses), (2) revoke the
        node's outbound leases through the PR 6 revocation path so
        holders reroute now, (3) steal queued-but-unstarted head tasks
        back and wait for the rest to finish, (4) force-checkpoint its
        restartable actors onto a SURVIVING store (``checkpoint_now`` →
        parts-shipped ``__ray_save__`` descriptors re-homed here — a
        checkpoint homed on the dying node would be dropped at restart),
        (5) migrate small sole-copy objects off the node (pull + re-home
        on the head store; larger ones stay lineage-reconstruction
        candidates), then (6) release the agent with ``drain_node`` so
        it exits cleanly.  Returns True when every phase landed inside
        the deadline (``drains_completed``); False on refusal or
        timeout (``drain_timeouts`` — the caller falls through to the
        existing hard-kill recovery, which is always correct, just
        costlier)."""
        if isinstance(node_id, str):
            node_id = NodeID(bytes.fromhex(node_id))
        if not self.config.elastic_drain:
            return False
        if deadline_s is None:
            deadline_s = self.config.drain_deadline_s
        deadline = time.monotonic() + max(0.2, float(deadline_s))
        entry = pending = None
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive or node is self.head_node:
                return False
            if node.draining:
                pending = self._node_drains.get(node_id)
            else:
                node.draining = True
                entry = [threading.Event(), False, deadline]
                self._node_drains[node_id] = entry
            agent = node.agent
        if entry is None:
            # Another drain is in flight (scale-down racing a preemption
            # notice): wait for ITS conclusion — past its own deadline,
            # not just ours (it concludes on its own schedule) — and
            # return its REAL outcome, instead of failing into a hard
            # kill mid-migration or mislabeling a timed-out drain as a
            # success.  A missing entry means that drain already
            # concluded; just let the caller terminate.
            if pending is None:
                return True
            wait_s = max(float(deadline_s),
                         pending[2] - time.monotonic()) + 10.0
            if pending[0].wait(max(0.2, wait_s)):
                return bool(pending[1])
            return False  # wedged past its own deadline: hard fallback
        try:
            completed = self._drain_node_inner(node, agent, deadline,
                                               deadline_s, reason)
            entry[1] = completed
            return completed
        finally:
            with self.lock:
                self._node_drains.pop(node_id, None)
            entry[0].set()

    def _drain_node_inner(self, node, agent, deadline,
                          deadline_s, reason) -> bool:
        with self.lock:
            for w in list(node.all_workers.values()):
                if w.dead:
                    continue
                holder = w.client_lease
                if holder is not None:
                    # Leased-out worker: revoke exactly as node death
                    # would — the holder retries/reroutes everything the
                    # lease carried, but NOW, against a still-healthy
                    # cluster, instead of at the kill.
                    if not holder.dead \
                            and self.config.decentralized_dispatch:
                        self.lease_revocations += 1
                        self._queue_send(holder, ("lease_revoke",
                                                  [w.worker_id.hex()]))
                    w.client_lease = None
                    self._end_lease_locked(w)
                elif w.actor_id is None and w.inflight:
                    # Head-dispatched plain tasks: reclaim what has not
                    # started; what is already running gets the rest of
                    # the deadline to finish.
                    stealable = [tid for tid, r in w.inflight.items()
                                 if not r.is_actor_creation]
                    if stealable:
                        self._queue_send(w, ("steal", 0, stealable))
            self._request_dispatch_locked()
        idle_ok = self._drain_wait_idle(node, deadline)
        ck_ok = self._drain_checkpoint_actors(node, deadline)
        mig_ok = self._drain_migrate_objects(node, deadline)
        completed = idle_ok and ck_ok and mig_ok
        with self.lock:
            if completed:
                self.drains_completed += 1
            else:
                self.drain_timeouts += 1
        if agent is not None and not agent.dead:
            # Release the agent (it exits cleanly; sent even after a
            # timeout — best effort beats nothing, and the hard-kill
            # recovery covers whatever was left).  Caps-gated per the
            # PR 3 convention: an old agent that would ignore the verb
            # is never probed — its node falls to the legacy teardown.
            agent_drain_caps = tuple(agent.info.get("agent_caps") or ())
            if "drain_node" in agent_drain_caps:
                try:
                    agent.send(("drain_node", float(deadline_s), reason))
                except Exception:
                    pass
        return completed

    def _drain_wait_idle(self, node: NodeState, deadline: float) -> bool:
        """Wait (bounded) for the node's plain workers to finish their
        in-flight head-dispatched tasks; stolen tasks re-enqueue
        elsewhere on their own."""
        while time.monotonic() < deadline:
            with self.lock:
                busy = any(w.inflight and not w.dead
                           and w.actor_id is None
                           for w in node.all_workers.values())
            if not busy:
                return True
            time.sleep(0.05)
        return False

    def _drain_checkpoint_actors(self, node: NodeState,
                                 deadline: float) -> bool:
        """Force an immediate ``__ray_save__`` of every restartable
        actor on the node; the worker ships parts the head re-homes on
        its own store (actor_checkpoint handler).  True when every ack
        landed before the deadline."""
        targets = []
        with self.lock:
            if not self.config.recovery:
                return True
            for aid, actor in self.actors.items():
                w = actor.worker
                if (actor.status == ALIVE and w is not None and not w.dead
                        and w.node is node and actor.restarts_left != 0):
                    ev = threading.Event()
                    self._drain_ck_events[aid] = ev
                    targets.append((aid, w, ev))
            for aid, w, _ev in targets:
                self._queue_send(w, ("checkpoint_now", aid))
        ok = True
        for aid, _w, ev in targets:
            left = deadline - time.monotonic()
            if left <= 0 or not ev.wait(left):
                ok = False
                break
        with self.lock:
            for aid, _w, _ev in targets:
                self._drain_ck_events.pop(aid, None)
        return ok

    def _drain_migrate_objects(self, node: NodeState,
                               deadline: float) -> bool:
        """Migrate sole-copy objects homed in the draining node's store:
        READY shm segments at most ``drain_migrate_max_bytes`` are
        pulled over the data plane and re-homed on the head's surviving
        store (descriptor updated in place — consumers resolving through
        the owner directory see the new home; a stale cached pull falls
        back to getparts, which serves the new copy).  Bigger objects
        are left as lineage-reconstruction candidates.  True when every
        candidate moved (or none needed to) before the deadline."""
        cap = self.config.drain_migrate_max_bytes
        if node.store_id == self.store_id:
            # In-process test node: it shares the HEAD's store, which
            # survives the node — nothing to move (and "migrating"
            # head-homed segments onto themselves would be an
            # unlink-recreate race).
            return True
        victims = []
        with self.lock:
            for oid, st in self.objects.items():
                d = st.descr
                # SPILLED counts too: the node's spill files die with it
                # exactly like its shm pages, and the object server
                # attaches them by (absolute) path just like any
                # segment, so the same pull migrates both.
                if (st.status == READY and d is not None
                        and d[0] in (protocol.SHM, protocol.SPILLED)
                        and len(d) > 3
                        and d[3] == node.store_id and d[2] <= cap):
                    st.pins += 1  # no free/spill mid-migration
                    victims.append((oid, st, d))
        if not victims:
            return True

        def _migrate_one(item) -> bool:
            oid, st, d = item
            moved = None
            if time.monotonic() < deadline:
                try:
                    meta, bufs = self._fetch_parts(d)
                    moved = self._store_parts_locally(oid, meta, bufs)
                except Exception:
                    moved = None  # reconstruction candidate
            with self.lock:
                st.pins -= 1
                if moved is not None:
                    if st.status == READY and st.descr is d:
                        st.descr = moved
                        # Frees now unlink the NEW home (head-local),
                        # not the dead creator's pooled pages.
                        st.creator = None
                        self.objects_migrated += 1
                    else:
                        # Freed/re-written while we copied: drop the
                        # orphan replica (spill-degraded copies are
                        # plain files).
                        try:
                            if moved[0] == protocol.SPILLED:
                                os.unlink(moved[1])
                            else:
                                self.shm.unlink(moved[1], moved[2],
                                                reusable=False)
                        except Exception:
                            pass
                self._maybe_free_locked(oid, st)
            return moved is not None

        # The pulls are independent and all race the SAME closing
        # deadline: overlap them (the node's object server already
        # serves concurrent getparts — PR 7's striped pull is built on
        # it) so N victims cost ~N/pool transfers instead of a straight
        # sum that turns a beatable spot warning window into a
        # drain_timeout + avoidable reconstructions.
        ok = True
        with ThreadPoolExecutor(
                max_workers=min(4, len(victims)),
                thread_name_prefix="rtpu-drain-migrate") as pool:
            for done in pool.map(_migrate_one, victims):
                if not done:
                    ok = False
        return ok

    # -------------------------------------------------- runtime accessor --
    def is_worker(self):
        return False

    def add_local_reference(self, object_id: ObjectID):
        coll = getattr(self._tls, "reg_collector", None)
        if coll is not None:
            # Deserialization in progress: refs created by unpickling are
            # registered as ONE batch under one lock when the load
            # finishes (a 10k-ref container would otherwise take the
            # runtime lock 10k times; reference: reference_count.cc
            # batches borrower registration per message).
            coll.append((object_id, 1))
            return
        with self.lock:
            st = self.objects.get(object_id)
            if st is None:
                st = self.objects[object_id] = ObjectState()
            st.local_refs += 1

    def _begin_bulk_refs(self):
        prev = getattr(self._tls, "reg_collector", None)
        self._tls.reg_collector = []
        return prev

    def _end_bulk_refs(self, prev):
        coll = getattr(self._tls, "reg_collector", None)
        self._tls.reg_collector = prev
        if not coll:
            return
        if prev is not None:
            prev.extend(coll)  # nested load: the outermost applies
            return
        with self.lock:
            # Increments first: a (+1, -1) pair for the same oid must
            # never transit zero regardless of arrival order.
            for oid, delta in coll:
                if delta <= 0:
                    continue
                st = self.objects.get(oid)
                if st is None:
                    st = self.objects[oid] = ObjectState()
                st.local_refs += 1
            for oid, delta in coll:
                if delta > 0:
                    continue
                st = self.objects.get(oid)
                if st is not None:
                    st.local_refs -= 1
                    self._maybe_free_locked(oid, st)

    def remove_local_reference(self, object_id: ObjectID):
        if self._stopped:
            return
        coll = getattr(self._tls, "reg_collector", None)
        if coll is not None:
            # Mid-deserialization drop (a load-time __del__): defer it
            # with the batched increments — applying it immediately while
            # the matching +1 sits in the collector could free an object
            # that is still referenced.
            coll.append((object_id, -1))
            return
        with self.lock:
            st = self.objects.get(object_id)
            if st is None:
                return
            st.local_refs -= 1
            self._maybe_free_locked(object_id, st)

    def on_ref_serialized(self, object_id: ObjectID):
        # Collect-only: refs pickled while a collection is active (task-arg /
        # put serialization) are recorded; the submit/put path pins them under
        # the lock and the completion/free path unpins (simplified borrow
        # protocol vs reference_count.cc).  Refs pickled outside a collection
        # (user manually pickling a ref) are NOT pinned — as in the
        # reference, out-of-band ref serialization needs an owner keeping the
        # object alive.
        collector = getattr(self._tls, "ref_collector", None)
        if collector is not None:
            collector.append(object_id.binary())

    def begin_ref_collection(self):
        self._tls.ref_collector = []

    def end_ref_collection(self) -> list:
        out = getattr(self._tls, "ref_collector", None) or []
        self._tls.ref_collector = None
        return out

    def _pin_nested_locked(self, nested: list):
        for b in nested:
            oid = ObjectID(b)
            st = self.objects.get(oid)
            if st is None:
                st = self.objects[oid] = ObjectState()
            st.pins += 1

    def _unpin_nested_locked(self, nested: list):
        for b in nested:
            oid = ObjectID(b)
            st = self.objects.get(oid)
            if st is not None:
                st.pins -= 1
                self._maybe_free_locked(oid, st)

    def _maybe_free_locked(self, oid: ObjectID, st: ObjectState):
        if st.refcount() <= 0 and not st.futures and not st.waiters:
            self.objects.pop(oid, None)
            if st.descr is not None and st.descr[0] == protocol.SPILLED:
                home = (st.descr[3] if len(st.descr) > 3
                        else self.store_id)
                if home == self.store_id:
                    try:
                        os.unlink(st.descr[1])
                    except OSError:
                        pass
                else:
                    # Spill file lives on the owner node: route the
                    # unlink there (the agent's unlink handles absolute
                    # paths).
                    agent = self._agents.get(home)
                    if agent is not None and not agent.dead:
                        self._queue_agent_send(
                            agent, ("unlink_segment", st.descr[1],
                                    st.descr[2]))
            if st.descr is not None and st.descr[0] == protocol.SHM:
                home = st.descr[3] if len(st.descr) > 3 else self.store_id
                cw = st.creator
                if cw is not None and not cw.dead:
                    # A worker's store created the segment: route the free
                    # there so its pages can be pooled for in-place reuse
                    # (shipped segments may be mapped elsewhere — the worker
                    # then just closes + unlinks).  Conflated: a burst of
                    # frees rides one ("batch", ...) frame.  Queueing
                    # cannot fail; if delivery later fails, the worker-
                    # death path reroutes buffered frees to the store-
                    # side fallback (_reroute_dead_worker_frees_locked).
                    self._queue_send(cw, ("free_segment", st.descr[1],
                                          st.descr[2], not st.shipped))
                if cw is None or cw.dead:
                    if home == self.store_id:
                        self.shm.unlink(st.descr[1], st.descr[2],
                                        reusable=(not st.shipped
                                                  and st.creator is None))
                    else:
                        agent = self._agents.get(home)
                        if agent is not None and not agent.dead:
                            self._queue_agent_send(
                                agent, ("unlink_segment", st.descr[1],
                                        st.descr[2]))
            if st.segment is not None:
                st.segment.close()
            if st.nested_ids:
                nested, st.nested_ids = st.nested_ids, []
                self._unpin_nested_locked(nested)
            self._release_lineage_for_locked(oid)

    # ------------------------------------------------------------ objects --
    def serialize_value(self, value, object_id: ObjectID):
        # One serialization pass; shm buffers are memcpy'd exactly once,
        # directly into the segment (plasma create→write-in-place→seal).
        res = serialization.dumps_adaptive(
            value, self.config.max_inline_object_size)
        if res[0] == "inline":
            return (protocol.INLINE, res[1])
        try:
            name, size = self.shm.create_from_parts(object_id, res[1],
                                                    res[2])
        except MemoryError:
            # Store full: spill LRU unpinned residents to disk, then retry;
            # if still no room, write the new object straight to disk
            # (reference: LocalObjectManager spilling + the plasma
            # CreateRequestQueue fallback, local_object_manager.h:41).
            need = (sum(len(b) for b in res[2]) + len(res[1]) + 65536)
            self._spill_objects(need)
            try:
                name, size = self.shm.create_from_parts(object_id, res[1],
                                                        res[2])
            except MemoryError:
                path, size = self.shm.create_spilled(
                    object_id, res[1], res[2], self.spill_dir)
                return (protocol.SPILLED, path, size, self.store_id)
        return (protocol.SHM, name, size, self.store_id)

    def _clear_stale_put_segment(self, oid: ObjectID):
        """A failed direct push can strand the oid's canonical segment
        (the server committed but the ack was lost, or the abort cleanup
        is still draining server-side) — and the put_parts FALLBACK for
        the same oid then collides with it.  This put owns the name:
        clear any pending reservation, and for a committed remnant
        unlink it (restoring accounting) before assembling the
        fallback."""
        name = self.shm.segment_name(oid)
        path = os.path.join(self.shm._dir, name)
        # The spill-degraded reservation commits under spill_dir instead.
        spath = (os.path.join(self.spill_dir, name)
                 if self.spill_dir else None)
        spath = spath if spath and os.path.exists(spath) else None
        if not os.path.exists(path) and spath is None:
            return
        pending = False
        try:
            pending = object_transfer._puts_for(self.shm).abort(name)
        except Exception:
            pass
        if pending:
            # The reservation teardown (possibly deferred to the last
            # draining stripe writer) owns the file + accounting; wait
            # briefly for it to land rather than double-rolling-back.
            deadline = time.monotonic() + 2.0
            while os.path.exists(path) and time.monotonic() < deadline:
                time.sleep(0.01)
            return
        if spath is not None:
            try:
                os.unlink(spath)  # spill files are not store-accounted
            except OSError:
                pass
        try:
            size = os.stat(path).st_size
        except OSError:
            return  # shm remnant already gone
        self.shm.unlink(name, size)

    def _store_parts_locally(self, oid: ObjectID, meta: bytes, bufs):
        """Pre-serialized parts into the driver store (client puts),
        with the same spill fallback as serialize_value."""
        views = [memoryview(b) for b in bufs]
        self._clear_stale_put_segment(oid)

        def create():
            try:
                return self.shm.create_from_parts(oid, meta, views)
            except FileExistsError:
                # Raced a direct-push remnant that landed after the
                # clear above: clear again and retry once.
                self._clear_stale_put_segment(oid)
                return self.shm.create_from_parts(oid, meta, views)

        try:
            name, size = create()
        except MemoryError:
            need = sum(len(b) for b in bufs) + len(meta) + 65536
            self._spill_objects(need)
            try:
                name, size = create()
            except MemoryError:
                path, size = self.shm.create_spilled(
                    oid, meta, views, self.spill_dir)
                return (protocol.SPILLED, path, size, self.store_id)
        return (protocol.SHM, name, size, self.store_id)

    def _spill_objects(self, need_bytes: int) -> int:
        """Move LRU-ish unpinned READY resident objects to spill_dir until
        ``need_bytes`` of shm is freed (or no victims remain).  Insertion
        order of the object table approximates LRU (plasma's eviction
        policy is LRU too, eviction_policy.h)."""
        freed = 0
        with self.lock:
            victims = []
            total = 0
            for oid, st in self.objects.items():
                if (st.status == READY and st.pins == 0
                        and st.descr is not None
                        and st.descr[0] == protocol.SHM
                        and not st.shipped
                        and (len(st.descr) < 4
                             or st.descr[3] == self.store_id)
                        and st.segment is None):
                    victims.append((oid, st))
                    total += st.descr[2]
                    if total >= need_bytes:
                        break
            # Pin the victims: a concurrent free or a second spill pass
            # must not touch them while the copies run WITHOUT the lock
            # (multi-GB disk copies must not stall the whole driver).
            for _oid, st in victims:
                st.pins += 1
        done = []
        for oid, st in victims:
            name, size = st.descr[1], st.descr[2]
            try:
                path = self.shm.spill(name, size, self.spill_dir)
            except OSError:
                path = None
            done.append((oid, st, name, size, path))
            if path is not None:
                freed += size
        with self.lock:
            for oid, st, name, size, path in done:
                st.pins -= 1
                if path is not None:
                    creator = st.creator
                    st.descr = (protocol.SPILLED, path, size,
                                self.store_id)
                    st.creator = None
                    if creator is not None and not creator.dead:
                        # The creating worker may still hold the (now
                        # deleted) file's pages mapped in its pool: let go.
                        self._queue_send(creator, ("free_segment",
                                                   name, size, False))
                self._maybe_free_locked(oid, st)
        return freed

    def put_object(self, value):
        from ray_tpu._private.object_ref import ObjectRef

        oid = ObjectID.for_put()
        self.begin_ref_collection()
        try:
            descr = self.serialize_value(value, oid)
        finally:
            nested = self.end_ref_collection()
        with self.lock:
            st = self.objects.get(oid)
            if st is None:
                st = self.objects[oid] = ObjectState()
            st.status = READY
            st.descr = descr
            st.value = value
            st.has_value = True
            st.local_refs += 1  # the caller's ref, counted under the lock
            st.nested_ids = nested
            self._pin_nested_locked(nested)
        return ObjectRef(oid, _register=False)

    def _register_put_locked(self, oid: ObjectID, st: ObjectState,
                             descr, ok: bool):
        """Publish a client-put descriptor: READY + wake waiters, but —
        unlike task-result completion — WITHOUT the maybe-free check: a
        fresh put's refcount is 0 until the client's addref (the very
        next message on its FIFO connection) lands, and freeing in that
        window would strand the ref forever."""
        st.status = READY if ok else ERRORED
        st.descr = descr
        self._gcs_dirty += 1  # object table rides the GCS snapshot now
        futures, st.futures = st.futures, []
        waiters, st.waiters = st.waiters, []
        for f in futures:
            if not f.done():
                f.set_result(oid)
        for cb in waiters:
            cb(oid)

    def _complete_object_locked(self, oid: ObjectID, descr, ok: bool,
                                creator=None):
        st = self.objects.get(oid)
        if st is None:
            st = self.objects[oid] = ObjectState()
        st.status = READY if ok else ERRORED
        st.descr = descr
        self._gcs_dirty += 1  # object table rides the GCS snapshot now
        if creator is not None and descr is not None \
                and descr[0] == protocol.SHM:
            st.creator = creator
        futures, st.futures = st.futures, []
        waiters, st.waiters = st.waiters, []
        for f in futures:
            if not f.done():
                f.set_result(oid)
        for cb in waiters:
            cb(oid)
        self._maybe_free_locked(oid, st)

    def object_future(self, object_id: ObjectID) -> Future:
        """Future resolving to the deserialized value (driver only)."""
        inner = Future()
        with self.lock:
            st = self.objects.get(object_id)
            if st is None:
                raise exc.ObjectFreedError(object_id=object_id.hex(),
                                           owner="driver", phase="get")
            if st.status != PENDING:
                inner.set_result(object_id)
            else:
                st.futures.append(inner)
        outer = Future()

        def _chain(f):
            try:
                outer.set_result(self._materialize(object_id))
            except BaseException as e:  # noqa: BLE001
                outer.set_exception(e)

        inner.add_done_callback(_chain)
        return outer

    def _materialize(self, oid: ObjectID, _recovering=False):
        with self.lock:
            st = self.objects.get(oid)
            if st is None:
                raise exc.ObjectFreedError(object_id=oid.hex(),
                                           owner="driver", phase="get")
            if st.has_value and st.status == READY:
                return st.value
            descr = st.descr
            if descr is not None and descr[0] == protocol.SHM:
                # Marked before attaching (which happens outside the lock):
                # a concurrent free must not pool and reuse the segment's
                # inode while we are mapping/deserializing it.
                st.shipped = True
        prev = self._begin_bulk_refs()
        try:
            value = self._materialize_value(oid, descr, _recovering)
        finally:
            self._end_bulk_refs(prev)
        with self.lock:
            st2 = self.objects.get(oid)
            if st2 is not None:
                st2.value = value
                st2.has_value = True
        return value

    def _materialize_value(self, oid: ObjectID, descr, _recovering):
        kind = descr[0]
        if kind == protocol.INLINE:
            value = serialization.loads_inline(descr[1])
        elif kind == protocol.PARTS:
            value = serialization.loads(descr[1], descr[2])
        elif kind == protocol.SHM and len(descr) > 3 \
                and descr[3] != self.store_id:
            # Segment lives in another node's store: ship its parts
            # (reference: ObjectManager::Pull via the owner's directory).
            try:
                meta, bufs = self._fetch_parts(descr)
            except exc.ObjectLostError:
                # Home store is gone: rebuild by lineage re-execution
                # (reference: object_recovery_manager.h:41).
                if _recovering or not self._recover_and_wait(oid):
                    raise
                return self._materialize(oid, _recovering=True)
            value = serialization.loads(meta, bufs)
            with self.lock:
                st2 = self.objects.get(oid)
                if st2 is not None:
                    st2.shipped = True
        elif kind == protocol.SHM:
            try:
                seg = self.shm.attach(descr[1])
            except FileNotFoundError:
                with self.lock:
                    st3 = self.objects.get(oid)
                    respilled = (st3 is not None and st3.descr is not None
                                 and st3.descr[0] == protocol.SPILLED)
                if respilled:
                    # Raced with the spiller: the object moved to disk
                    # between descriptor read and attach.
                    return self._materialize(oid, _recovering=_recovering)
                if _recovering or not self._recover_and_wait(oid):
                    raise exc.ObjectLostError(object_id=oid.hex(),
                                              home=self.store_id,
                                              owner="driver", phase="get")
                return self._materialize(oid, _recovering=True)
            value = seg.deserialize()
            with self.lock:
                st2 = self.objects.get(oid)
                if st2 is not None:
                    st2.segment = seg
        elif kind == protocol.SPILLED:
            # Restore from external storage (reference:
            # local_object_manager.h restore path).  Spill files written
            # by a REMOTE node only exist there: ship the parts.
            home = descr[3] if len(descr) > 3 else self.store_id
            if home != self.store_id and not os.path.exists(descr[1]):
                meta, bufs = self._fetch_parts(descr)
                value = serialization.loads(meta, bufs)
            else:
                seg = self.shm.attach_path(descr[1])
                value = seg.deserialize()
                with self.lock:
                    st2 = self.objects.get(oid)
                    if st2 is not None:
                        st2.segment = seg
        else:  # error
            raise serialization.loads_inline(descr[1])
        return value

    def _recovery_on(self) -> bool:
        return self.config.recovery and self.config.lineage_enabled

    def _register_lineage_locked(self, spec: dict):
        if not self._recovery_on():
            return
        if "actor_id" in spec or spec.get("num_returns", 0) <= 0:
            return  # actor methods have side effects; no re-execution
        # Keyed by the 12-byte task prefix: an ObjectID carries only the
        # prefix of its creating TaskID (ids.py), so recovery must be able
        # to go oid -> lineage without the full 16-byte task id.  The
        # table bounds itself: entries evicted for the byte budget get
        # their pinned spec resources released here, at the caller's
        # locking level (table _lock is a leaf; it runs no callbacks) —
        # EXCEPT specs whose task is still queued/in flight: their
        # nested-ref pins and by-value arg segments are live execution
        # state, released by the completion path instead (which
        # re-checks lineage membership and finds the entry gone).
        for old in self.lineage.record(
                spec, default_retries=self.config.default_max_retries):
            if old["spec"]["task_id"] not in self.tasks:
                self._release_spec_resources_locked(old["spec"])

    def _release_lineage_for_locked(self, oid: ObjectID):
        entry = self.lineage.release(oid.binary())
        if entry is not None:
            # The last return object is gone: nothing can ask for
            # re-execution anymore, so the nested-ref pins and by-value arg
            # segments held for it are released now.
            self._release_spec_resources_locked(entry["spec"])

    def _oid_from_segment_name(self, name: str) -> Optional[ObjectID]:
        """Segment names are rtpu-<session>-<oid hex> (shm_store.py;
        one naming-rule implementation, recovery.seg_oid_hex)."""
        oid_hex = recovery.seg_oid_hex(name)
        return None if oid_hex is None else ObjectID(bytes.fromhex(oid_hex))

    def _store_is_dead(self, store_hex: str) -> bool:
        if store_hex == self.store_id:
            return False
        agent = self._agents.get(store_hex)
        return agent is None or agent.dead

    def _try_recover_locked(self, oid: ObjectID) -> bool:
        """Queue re-execution of ``oid``'s creating task (reference:
        ObjectRecoveryManager::RecoverObject).  Returns False when
        recovery is off, no lineage exists (puts, actor results,
        released/evicted lineage), or the entry's reconstruction budget
        — per-task max_retries, a SYSTEM-failure budget — is spent."""
        if not self._recovery_on():
            return False
        entry = self.lineage.get(oid.task_prefix())
        if entry is None:
            return False
        spec = entry["spec"]
        if spec["task_id"] in self.tasks:
            return True  # already re-executing
        if not self.lineage.note_attempt(oid.task_prefix()):
            return False  # depleted retries: the loss stands
        self.reconstructions += 1
        tid = TaskID(spec["task_id"])
        for i in range(spec["num_returns"]):
            oid_i = tid.object_id(i)
            sti = self.objects.get(oid_i)
            if sti is None:
                sti = self.objects[oid_i] = ObjectState(tid)
            elif sti.status != PENDING:
                sti.status = PENDING
                sti.descr = None
                sti.value = None
                sti.has_value = False
                sti.segment = None
                sti.shipped = False
        req = spec.get("resources") or {"CPU": 1.0}
        rec = TaskRecord(spec, req,
                         spec.get("max_retries",
                                  self.config.default_max_retries))
        _apply_strategy(rec, spec)
        self.tasks[spec["task_id"]] = rec
        # Recursively recover lost dependencies first: a dep whose segment
        # store died must be rebuilt before this task can run on it.
        for a in spec.get("args", []):
            if isinstance(a, tuple) and a and a[0] == "ref":
                dep = ObjectID(a[1])
                dst = self.objects.get(dep)
                if (dst is None
                        or (dst.status == READY and dst.descr is not None
                            and dst.descr[0] == protocol.SHM
                            and len(dst.descr) > 3
                            and self._store_is_dead(dst.descr[3]))):
                    self._try_recover_locked(dep)
        self._resolve_deps_locked(rec)
        if rec.deps_pending == 0:
            self._enqueue_pending_locked(rec)
            self._dispatch_locked()
        self.task_events.append(
            {"task_id": spec["task_id"].hex(), "name": spec.get("name"),
             "state": "RECONSTRUCTING", "time": time.time()})
        return True

    def _recover_and_wait(self, oid: ObjectID, timeout=60.0) -> bool:
        """Trigger lineage recovery and block until the object is READY
        again.  Call WITHOUT the runtime lock.  A False return is a
        counted reconstruction failure — the caller surfaces
        ObjectLostError (zero failures counted while recovery is off:
        the refusal is then the legacy path, not a failure of it)."""
        ev = threading.Event()
        ok = False
        known = False
        try:
            with self.lock:
                # "Known" scopes the failure counter: a refusal for an
                # object the head never owned (a worker-owned segment
                # relayed through getparts) is not a head recovery
                # failure — the OWNER's lineage may still rebuild it.
                known = (oid in self.objects
                         or self.lineage.get(oid.task_prefix())
                         is not None)
                if not self._try_recover_locked(oid):
                    return False
                st = self.objects.get(oid)
                if st is None:
                    return False
                if st.status != PENDING:
                    ok = st.status == READY
                    return ok
                st.waiters.append(lambda _oid: ev.set())
            if not ev.wait(timeout):
                return False
            with self.lock:
                st = self.objects.get(oid)
                ok = st is not None and st.status == READY
                return ok
        finally:
            if not ok and known and self._recovery_on():
                with self.lock:
                    self.reconstruction_failures += 1

    def _recover_for_worker(self, worker: "WorkerHandle",
                            oid: ObjectID) -> bool:
        """Run lineage recovery on a WORKER's behalf (the getparts relay
        hit a dead store), releasing the requester's lease slot for the
        duration — the same credit the blocked/unblocked envelope moves.
        Without this, a node full of workers all blocked fetching args
        from a dead peer deadlocks recovery: the re-executed producers
        would have no slot to run on (the getters hold them all), which
        is exactly the cluster state after a node loss."""
        released = False
        with self.lock:
            if worker.lease_req is not None and not worker.released \
                    and worker.lease_pg is None and not worker.dead:
                worker.blocked = True
                worker.node.release(worker.lease_req)
                worker.released = True
                released = True
                self._request_dispatch_locked()
        try:
            return self._recover_and_wait(oid)
        finally:
            if released:
                with self.lock:
                    if not worker.dead and worker.lease_req is not None \
                            and worker.released:
                        worker.node.acquire(worker.lease_req)
                        worker.released = False
                    worker.blocked = False

    def _fetch_parts(self, descr):
        """Serialized (meta, buffers) of a SHM descriptor, shipping across
        stores when the segment is not locally attachable.  Blocking: call
        without the runtime lock held."""
        home = descr[3] if len(descr) > 3 else self.store_id
        if home == self.store_id:
            if descr[0] == protocol.SPILLED:
                seg = self.shm.attach_path(descr[1])
            else:
                seg = self.shm.attach(descr[1])
            try:
                meta, bufs = seg.raw_parts()
                return bytes(meta), [bytes(b) for b in bufs]
            finally:
                seg.close()
        with self.lock:
            agent = self._agents.get(home)
        if agent is None or agent.dead:
            raise exc.ObjectLostError(object_id=_seg_oid_hex(descr[1]),
                                      home=home, phase="pull")
        addr = agent.info.get("object_addr")
        if addr:
            # Direct chunked pull from the home node's object server,
            # striped/pooled, received straight into a local shm mapping
            # (one copy) — the head never touches the payload
            # (object_manager.h:206).  The returned buffers are zero-copy
            # views over the received mapping; they keep it alive.
            caps = tuple(agent.info.get("object_caps") or ())
            try:
                seg = object_transfer.pull_to_segment(
                    self._puller, self.shm, home, addr, descr[1],
                    caps=caps)
                return seg.raw_parts()
            except exc.ObjectLostError as e:
                if getattr(e, "phase", None) != "stalled":
                    raise
                # Stalled direct pull (deadline + retries exhausted):
                # HEDGE to the relay instead of propagating — the
                # agent's control link may still move even when its
                # object server does not.
                protocol.note_net_event("hedged_fetches")
            except Exception:
                pass  # conn trouble: fall back to the head relay
        with self.lock:
            self.relayed_segments += 1
        cfg = self.config
        relay_timeout = (max(2.0 * cfg.net_stall_timeout_s, 5.0)
                         if cfg.failure_detection else 30.0)
        return agent.request_segment(descr[1], timeout=relay_timeout)

    def get_objects(self, refs, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            oid = ref.id()
            ev = threading.Event()
            with self.lock:
                st = self.objects.get(oid)
                if st is None:
                    raise exc.ObjectFreedError(object_id=oid.hex(),
                                               owner="driver", phase="get")
                if st.status == PENDING:
                    st.waiters.append(lambda _oid, ev=ev: ev.set())
                else:
                    ev.set()
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not ev.wait(remaining):
                raise exc.GetTimeoutError(
                    f"Timed out getting {oid.hex()} after {timeout}s")
            out.append(self._materialize(oid))
        return out

    def wait_objects(self, refs, num_returns=1, timeout=None,
                     fetch_local=True):
        ids = [r.id() for r in refs]
        done_ev = threading.Event()
        state = {"ready": 0}
        with self.lock:
            pending = []
            for oid in ids:
                st = self.objects.get(oid)
                if st is None or st.status != PENDING:
                    state["ready"] += 1
                else:
                    pending.append(st)
            if state["ready"] < num_returns:
                def cb(_oid):
                    state["ready"] += 1
                    if state["ready"] >= num_returns:
                        done_ev.set()
                for st in pending:
                    st.waiters.append(cb)
            else:
                done_ev.set()
        done_ev.wait(timeout)
        ready, not_ready = [], []
        with self.lock:
            for ref, oid in zip(refs, ids):
                st = self.objects.get(oid)
                if st is None or st.status != PENDING:
                    ready.append(ref)
                else:
                    not_ready.append(ref)
        # Cap at num_returns for exact reference semantics
        if len(ready) > num_returns:
            not_ready = ready[num_returns:] + not_ready
            ready = ready[:num_returns]
        return ready, not_ready

    # -------------------------------------------------------- submission --
    def register_function(self, payload: bytes) -> str:
        func_id = serialization.dumps_inline(len(payload)).hex()[:8] + \
            __import__("hashlib").sha1(payload).hexdigest()[:16]
        with self.lock:
            if func_id not in self.functions:
                self.functions[func_id] = payload
                self._gcs_dirty += 1
        return func_id

    def submit_task(self, spec: dict):
        """Entry from RemoteFunction._remote (reference:
        python/ray/remote_function.py:241 → core_worker.cc:1819 SubmitTask)."""
        return self.submit_tasks([spec])[0]

    def submit_tasks(self, specs: List[dict]):
        """Bulk submission: register every spec under ONE lock
        acquisition, then run ONE dispatch pass (and one pump per
        distinct actor) over the whole batch — a fan-out burst pays
        O(1) lock/dispatch instead of O(n) (reference: the per-
        SchedulingKey amortization in direct_task_transport.cc).
        Returns one list of ObjectRefs per spec."""
        from ray_tpu._private.object_ref import ObjectRef

        self._submit_specs(specs, from_worker=False)
        out = []
        for spec in specs:
            tid = TaskID(spec["task_id"])
            out.append([ObjectRef(tid.object_id(i), _register=False)
                        for i in range(spec["num_returns"])])
        return out

    def _submit_specs(self, specs: List[dict], *, from_worker: bool,
                      submitter=None):
        """Shared bulk-registration core for driver submissions and the
        worker/client ("submit"/"submit_batch") path.  Per-spec
        invariants (TaskRecord, strategy parse, SUBMITTED event dicts,
        one shared timestamp) are built OUTSIDE the lock; only table
        writes run inside it, followed by one dispatch pass and one
        pump per distinct actor."""
        now = time.time()
        recs = []
        events = []
        for spec in specs:
            if from_worker and submitter is not None \
                    and spec.get("tmp_segments"):
                # The submitting worker's store created any by-value arg
                # segments in tmp_segments; frees are routed back there
                # (segment-pool reuse).
                spec["_creator_worker"] = submitter
            req = spec.get("resources") or {"CPU": 1.0}
            rec = TaskRecord(spec, req,
                             spec.get("max_retries",
                                      self.config.default_max_retries))
            _apply_strategy(rec, spec)
            recs.append(rec)
            events.append(
                {"task_id": spec["task_id"].hex(),
                 "name": spec.get("name"),
                 "state": "SUBMITTED", "time": now})
        with self.lock:
            dispatch_keys: List[tuple] = []
            actor_ids: List[bytes] = []
            if from_worker and self.config.decentralized_dispatch:
                # The decentralization observable: specs that reached the
                # head's scheduler over the wire.  Under a healthy lease
                # plane this stays bounded by lease-renewal/starvation
                # events, not task count (pinned by the acceptance test).
                self.head_brokered_submits += len(specs)
            for rec, ev in zip(recs, events):
                spec = rec.spec
                tid = TaskID(spec["task_id"])
                for i in range(spec["num_returns"]):
                    oid = tid.object_id(i)
                    st = self.objects.get(oid)
                    if st is None:
                        st = self.objects[oid] = ObjectState(tid)
                    else:
                        st.task_id = tid
                    # Count the submitter's reference NOW, under the lock
                    # — its ObjectRefs are built with _register=False
                    # (the driver's own, or the worker's whose __del__
                    # decrefs pair with this).  Otherwise a fast task
                    # could complete (IO thread) and be freed before the
                    # submitter's ref registers (the classic ownership
                    # race; reference: reference_count.cc AddOwnedObject
                    # happens atomically with submission).
                    if from_worker:
                        st.worker_refs += 1
                    else:
                        st.local_refs += 1
                if from_worker and spec.get("func_payload") is not None:
                    fid = spec["func_id"]
                    self.functions.setdefault(fid,
                                              spec.pop("func_payload"))
                self.tasks[spec["task_id"]] = rec
                # SUBMITTED must precede the RUNNING event that dispatch
                # may append below — state queries take the latest event
                # per task.
                self.task_events.append(ev)
                self._register_lineage_locked(spec)
                self._pin_nested_locked(spec.get("nested_refs", []))
                self._resolve_deps_locked(rec)
                if "actor_id" in spec:
                    aid = self._enqueue_actor_task_nopump_locked(rec)
                    if aid is not None:
                        actor_ids.append(aid)
                elif rec.deps_pending == 0:
                    self._enqueue_pending_locked(rec)
                    dispatch_keys.append(rec.sched_key)
            for aid in dict.fromkeys(actor_ids):
                self._pump_actor_locked(self.actors[aid])
            if dispatch_keys:
                keys = list(dict.fromkeys(dispatch_keys))
                if not self.config.decentralized_dispatch:
                    self._dispatch_locked()
                elif not from_worker and len(specs) == 1:
                    # Driver sync-submit fast path: one spec, dispatch its
                    # class inline (no thread hop on the latency path; the
                    # scan is already scoped to one shard).
                    self._dispatch_locked(keys)
                else:
                    # Burst: hand the scan to the dispatcher thread so
                    # this submitter's lock hold ends at registration.
                    self._request_dispatch_locked(keys)

    def _resolve_deps_locked(self, rec: TaskRecord):
        spec = rec.spec
        deps = []
        for slot in ("args",):
            for a in spec[slot]:
                if a[0] == "ref":
                    deps.append(ObjectID(a[1]))
        for a in spec.get("kwargs", {}).values():
            if a[0] == "ref":
                deps.append(ObjectID(a[1]))
        rec.deps_pending = 0
        for oid in deps:
            st = self.objects.get(oid)
            if st is None:
                # Unknown dependency: surface as lost at dispatch time.
                continue
            if st.status == PENDING:
                rec.deps_pending += 1
                st.waiters.append(
                    lambda _oid, rec=rec: self._dep_ready(rec))
            st.pins += 1  # pinned until the task finishes

    def _dep_ready(self, rec: TaskRecord):
        with self.lock:
            rec.deps_pending -= 1
            if rec.deps_pending == 0 and not rec.dispatched:
                if rec.actor_id is not None:
                    self._pump_actor_locked(self.actors[rec.actor_id])
                else:
                    self._enqueue_pending_locked(rec)
                    self._request_dispatch_locked([rec.sched_key])

    # -------------------------------------------------------- scheduling --
    # Sentinel for _pick_node_locked's pref parameter: "not computed yet"
    # (None is a valid computed preference).
    _PREF_UNSET = object()

    def _pick_node_locked(self, rec: TaskRecord,
                          pref=_PREF_UNSET) -> Optional[NodeState]:
        """Hybrid policy condensed (reference:
        scheduling/policy/hybrid_scheduling_policy.cc — prefer local until
        threshold, then best remote; spillback)."""
        spec = rec.spec
        strategy = spec.get("scheduling_strategy")
        if rec.pg_id is not None:
            pg = self.placement_groups.get(rec.pg_id)
            if pg is None or pg.removed:
                return None
            idx = rec.bundle_index if rec.bundle_index is not None else 0
            node_id = pg.reserved[idx]
            if node_id is None:
                return None
            # PG bundles reserved node resources at creation; tasks must
            # still fit within the bundle's own capacity (shadow-resource
            # model, placement_group_resource_manager.cc).
            if not self._pg_can_fit_locked(pg, idx, rec.requirements):
                return None
            node = self.nodes.get(node_id)
            return node if node and node.alive else None
        if strategy and strategy[0] == "node_affinity":
            node = self.nodes.get(NodeID(strategy[1]))
            if node and node.alive and node.can_fit(rec.requirements):
                return node
            if strategy[2]:  # soft
                pass
            else:
                return None
        if strategy and strategy[0] == "spread":
            best = None
            best_score = 0.0
            for nid in self.node_order:
                node = self.nodes[nid]
                if not node.alive or not node.can_fit(rec.requirements):
                    continue
                score = sum(
                    node.available.get(k, 0) / max(node.resources.get(k, 1),
                                                   1)
                    for k in rec.requirements)
                # Strictly-greater with an epsilon: float near-ties (and
                # exact ties) resolve to the earliest node in node_order,
                # so spread placement is deterministic and testable.
                if best is None or score > best_score + 1e-9:
                    best, best_score = node, score
            return best
        if pref is self._PREF_UNSET:
            pref = self._locality_pref_locked(rec)
        if pref is not None and pref[0].can_fit(rec.requirements):
            # Top-locality node has fresh capacity: place there.  The
            # hit/miss/bytes accounting happens at the dispatch site,
            # which also covers the pipelined-lease placements.
            return pref[0]
        head = self.nodes[self.node_order[0]]
        if head.alive and head.can_fit(rec.requirements):
            return head
        for nid in self.node_order[1:]:
            node = self.nodes[nid]
            if node.alive and node.can_fit(rec.requirements):
                return node
        return None

    def _node_for_store_locked(self, store_hex: str) -> Optional[NodeState]:
        """The node whose object store is ``store_hex`` (in-process test
        nodes share the head's store and map to the head node)."""
        if store_hex == self.store_id:
            return self.head_node
        agent = self._agents.get(store_hex)
        return agent.node if agent is not None and not agent.dead else None

    def _locality_pref_locked(
            self, rec: TaskRecord) -> Optional[Tuple[NodeState, int]]:
        """(top-locality node, argument bytes homed there), or None when
        locality does not apply — strategy/PG tasks, no sizeable homed
        args, or the feature switched off.  Walks the spec's arg/kwarg
        descriptors once per record: every SHM/SPILLED descriptor carries
        (size, home store_id), and a "ref" arg's descriptor is READY in
        the object table by pick time (deps resolved before enqueue).
        Reference: locality-aware lease selection in
        hybrid_scheduling_policy.cc via the owner's object directory
        (the head IS the directory here — Ownership, NSDI'21)."""
        if not self.config.locality_scheduling:
            return None
        if rec.pg_id is not None or rec.spec.get("scheduling_strategy"):
            return None
        homes = rec.locality_homes
        if homes is None:
            homes = {}
            spec = rec.spec
            for d in itertools.chain(spec.get("args", ()),
                                     (spec.get("kwargs") or {}).values()):
                if d and d[0] == "ref":
                    st = self.objects.get(ObjectID(d[1]))
                    d = st.descr if st is not None else None
                if (d is not None and len(d) > 3
                        and d[0] in (protocol.SHM, protocol.SPILLED)):
                    homes[d[3]] = homes.get(d[3], 0) + d[2]
            rec.locality_homes = homes
        if not homes:
            return None
        best = None
        best_bytes = 0
        for store, nbytes in homes.items():
            if nbytes < best_bytes or nbytes < self.config.locality_min_bytes:
                continue
            node = self._node_for_store_locked(store)
            if node is None or not node.alive:
                continue
            if best is None or nbytes > best_bytes:
                best, best_bytes = node, nbytes
        return None if best is None else (best, best_bytes)

    def _lend_node_locked(self, rec: "TaskRecord") -> Optional[NodeState]:
        """Over-capacity admission backed by BLOCKED workers — without
        this, a cluster fully packed with actors deadlocks the moment an
        actor blocks on tasks that need a slot (reference: extra workers
        for blocked ones, worker_pool.cc backpressured by
        ray_config_def.h:174-187).

        Bound: a blocked worker's RELEASED slot already re-entered
        ``available`` (the "blocked" handler), and this path additionally
        admits up to one lent slot per blocked worker (so ≤2x per blocked
        worker, capped by ``max_extra_blocked_workers`` per node).  The
        looser 2x bound is deliberate: the released slot may legally be
        consumed by a permanent holder (a new actor), and the tasks the
        blocker waits on must STILL be admissible or the deadlock
        returns.  CPU oversubscription is transient and OS-scheduled.
        Transient CPU leases only: permanent holders (actors, TPU
        workers, PG bundles) never ride a lent slot."""
        if rec.is_actor_creation or rec.pg_id is not None:
            return None
        if rec.spec.get("scheduling_strategy"):
            return None
        req = rec.requirements
        if any(k not in ("CPU", "memory") for k in req):
            return None
        for nid in self.node_order:
            node = self.nodes[nid]
            if not node.alive or node.draining:
                continue
            blocked = sum(1 for w in node.all_workers.values()
                          if w.blocked and not w.dead)
            if blocked <= 0:
                continue
            lend = min(blocked, self.config.max_extra_blocked_workers)
            if (node.available.get("CPU", 0.0) - req.get("CPU", 0.0)
                    >= -lend - 1e-9
                    and all(node.available.get(k, 0.0) >= v - 1e-9
                            for k, v in req.items() if k != "CPU")):
                return node
        return None

    def _sched_class(self, rec: "TaskRecord") -> tuple:
        strategy = rec.spec.get("scheduling_strategy")
        # pg targeting is already covered by (pg_id, bundle_index); for the
        # rest (node_affinity/spread) the whole tuple keys the class.
        skey = None if strategy and strategy[0] == "placement_group" \
            else repr(strategy)
        # Actor creations get singleton classes: their worker becomes the
        # actor, so plain tasks must never pipeline onto its lease.
        marker = rec.actor_id if rec.is_actor_creation else None
        # runtime_env is part of the class: env_vars and the pip venv are
        # baked into the worker process at spawn, so tasks with different
        # envs must never share a lease (reference: SchedulingKey
        # includes runtime_env hash).
        env = rec.spec.get("runtime_env") or {}
        ekey = None
        if env.get("env_vars") or env.get("pip"):
            parts = []
            if env.get("env_vars"):
                parts.append(repr(sorted(env["env_vars"].items())))
            if env.get("pip"):
                from ray_tpu._private.runtime_env_pip import pip_env_hash

                parts.append("pip=" + pip_env_hash(env["pip"]))
            ekey = "|".join(parts)
        return (tuple(sorted(rec.requirements.items())),
                rec.pg_id, rec.bundle_index, skey, marker, ekey)

    def _enqueue_pending_locked(self, rec: "TaskRecord"):
        if rec.sched_key is None:
            rec.sched_key = self._sched_class(rec)
        self.pending_tasks.setdefault(rec.sched_key, deque()).append(rec)

    def _dispatch_locked(self, keys=None):
        """Assign queued tasks to workers.  Two-step per scheduling class,
        mirroring the reference's lease model (direct_task_transport.h:75):
        first pipeline onto already-leased workers of the class (up to
        max_tasks_in_flight each — the lease holds the resources, so
        pipelined tasks cost no extra slots), then lease new workers while
        resources remain.

        ``keys`` scopes the pass to those scheduling classes (sharded
        dispatch: a submit only needs its own class scanned — nothing it
        did could unblock another class); None scans every class
        (resource-release events, where anything may now place)."""
        # Chaos syncpoint: a RAY_TPU_CHAOS "head:dispatch:N" rule takes
        # the head down deterministically mid-scheduling (no-op unless
        # the head process armed it — see _private/head_main.py).
        recovery.syncpoint("dispatch")
        if self._stopped:
            return
        if self.pending_pgs:
            self._try_reserve_pgs_locked()
        for key in (list(self.pending_tasks) if keys is None else keys):
            self._dispatch_class_locked(key)
        self._service_client_leases_locked()

    def _dispatch_class_locked(self, key):
        """One scheduling class's dispatch pass (the shard body)."""
        q = self.pending_tasks.get(key)
        if q is not None:
            while q:
                rec = q[0]
                if rec.cancelled or rec.dispatched:
                    q.popleft()
                    continue
                pref = self._locality_pref_locked(rec)
                node = self._pick_node_locked(rec, pref)
                worker = None
                if node is None:
                    # No free capacity: overflow onto existing leases
                    # (pipelining) rather than stall the class.  Fresh
                    # capacity is preferred so a long task can't head-of-
                    # line-block a short one while CPUs sit idle.  With a
                    # locality preference, a lease on the preferred node
                    # wins among the pipelinable candidates.
                    worker = self._find_pipelinable_worker_locked(
                        key, prefer_node=(pref[0] if pref else None))
                    if worker is None:
                        # Last resort: blocked workers lend their slots.
                        node = self._lend_node_locked(rec)
                        if node is None:
                            break  # same class behind cannot place either
                elif pref is not None and node is not pref[0]:
                    # Fresh capacity only AWAY from the argument bytes: a
                    # pipelinable leased worker already on the top-
                    # locality node beats it (the lease holds the
                    # resources there and the args need no transfer) —
                    # but only up to the pipeline depth cap; past it the
                    # fresh node wins (locality must never stall a class).
                    w = self._find_pipelinable_worker_locked(
                        key, prefer_node=pref[0])
                    if w is not None and w.node is pref[0]:
                        worker = w
                if worker is not None:
                    q.popleft()
                    self._count_locality_locked(pref, worker.node, rec)
                    self._assign_to_worker_locked(worker, rec)
                    continue
                use_pg = rec.pg_id is not None
                if use_pg:
                    pg = self.placement_groups.get(rec.pg_id)
                    self._pg_acquire_locked(pg, rec.bundle_index or 0,
                                            rec.requirements)
                else:
                    node.acquire(rec.requirements)
                tpu_chips = []
                n_tpu = int(rec.requirements.get("TPU", 0))
                if n_tpu > 0:
                    if len(node.tpu_free) < n_tpu:
                        # Chips still attached to retiring workers.
                        if use_pg:
                            self._pg_release_locked(pg, rec.bundle_index or 0,
                                                    rec.requirements)
                        else:
                            node.release(rec.requirements)
                        break
                    tpu_chips = node.tpu_free[:n_tpu]
                    node.tpu_free = node.tpu_free[n_tpu:]
                q.popleft()
                self._count_locality_locked(pref, node, rec)
                rec.node = node
                worker = self._lease_worker_locked(node, rec, tpu_chips)
                worker.lease_req = dict(rec.requirements)
                worker.lease_pg = ((rec.pg_id, rec.bundle_index or 0)
                                   if use_pg else None)
                # TPU workers are dedicated + retired after their task, and
                # actor-creation workers become the actor: neither joins the
                # pipelining pool.
                if not tpu_chips and not rec.is_actor_creation:
                    worker.lease_key = key
                    self.leased_workers.setdefault(key, []).append(worker)
                self._assign_to_worker_locked(worker, rec)
            if not q:
                self.pending_tasks.pop(key, None)

    def _count_locality_locked(self, pref, target: NodeState,
                               rec: TaskRecord):
        """Account one placement against its locality preference — at the
        dispatch commit point only, so an aborted placement attempt (TPU
        chips mid-retire) can't double-count on the retry pass.

        A hit is credited only when locality actually CHANGED the
        placement: landing on the preferred node when the head-first
        default would have picked it anyway (e.g. head-homed args on a
        single-node cluster, where no byte could ever cross the network)
        counts nothing, so locality_bytes_saved reflects genuinely
        avoided transfers."""
        if pref is None:
            return
        if target is not pref[0]:
            self.locality_misses += 1
            return
        default = None
        alive = 0
        for nid in self.node_order:
            node = self.nodes[nid]
            if not node.alive:
                continue
            alive += 1
            if default is None and node.can_fit(rec.requirements):
                default = node
        if alive < 2 or default is target:
            return  # placement could not have / did not change
        self.locality_hits += 1
        self.locality_bytes_saved += pref[1]

    def _find_pipelinable_worker_locked(
            self, key: tuple,
            prefer_node: Optional[NodeState] = None
    ) -> Optional[WorkerHandle]:
        """Least-loaded leased worker of the class with pipeline room.
        ``prefer_node`` (locality): a candidate on that node wins over a
        less-loaded one elsewhere, but NEVER past the depth cap — the
        cap bounds head-of-line blocking and locality must not bypass
        it."""
        lst = self.leased_workers.get(key)
        if not lst:
            return None
        depth = self.config.max_tasks_in_flight_per_worker
        best = None
        best_pref = None
        for w in lst:
            if w.dead or w.blocked or w.released or w.actor_id is not None \
                    or w.pending_force_kill is not None:
                continue
            if len(w.inflight) >= depth:
                continue
            if best is None or len(w.inflight) < len(best.inflight):
                best = w
            if prefer_node is not None and w.node is prefer_node and (
                    best_pref is None
                    or len(w.inflight) < len(best_pref.inflight)):
                best_pref = w
        return best_pref if best_pref is not None else best

    def _assign_to_worker_locked(self, worker: WorkerHandle,
                                 rec: TaskRecord):
        rec.node = worker.node
        rec.worker = worker
        rec.dispatched = True
        worker.last_dispatch_ts = time.monotonic()
        if self._send_task(worker, rec):
            worker.inflight[rec.spec["task_id"]] = rec
        elif not worker.inflight:
            self._end_lease_locked(worker)

    def _end_lease_locked(self, worker: WorkerHandle, reap=False):
        """Return the worker's lease: release its held resources and pool
        (or retire) the process (reference: ReturnWorker in
        direct_task_transport.cc / raylet lease return)."""
        node = worker.node
        if worker.lease_key is not None:
            lst = self.leased_workers.get(worker.lease_key)
            if lst is not None:
                try:
                    lst.remove(worker)
                except ValueError:
                    pass
                if not lst:
                    self.leased_workers.pop(worker.lease_key, None)
            worker.lease_key = None
        if worker.lease_req is not None and node is not None:
            if not worker.released:
                if worker.lease_pg is not None:
                    pg = self.placement_groups.get(worker.lease_pg[0])
                    if pg is not None and not pg.removed:
                        self._pg_release_locked(pg, worker.lease_pg[1],
                                                worker.lease_req)
                else:
                    node.release(worker.lease_req)
        worker.lease_req = None
        worker.lease_pg = None
        worker.lease_expiry = None
        worker.released = False
        worker.blocked = False
        had_tpu = bool(worker.tpu_chips)
        if had_tpu and node is not None:
            node.tpu_free.extend(worker.tpu_chips)
            worker.tpu_chips = []
        worker.idle_since = time.monotonic()
        if reap or had_tpu:
            # TPU workers are dedicated: the chip set is baked into the
            # process env at spawn, so retire rather than cache.
            self._kill_worker_locked(worker)
        elif not worker.dead:
            worker.node.idle_workers.setdefault(worker.env_key, []).append(
                worker)

    def _env_key_for(self, rec: TaskRecord, tpu_chips) -> str:
        env = rec.spec.get("runtime_env") or {}
        key = repr(sorted(env.get("env_vars", {}).items()))
        if env.get("pip"):
            from ray_tpu._private.runtime_env_pip import pip_env_hash

            key += f"|pip={pip_env_hash(env['pip'])}"
        if env.get("working_dir"):
            # Content hash, not path: edited directories must not reuse
            # idle workers that extracted the previous package.
            key += f"|wd={self._package_working_dir(env['working_dir'])}"
        if tpu_chips:
            key += f"|tpu={','.join(map(str, tpu_chips))}"
        return key

    def _package_working_dir(self, path: str) -> str:
        """Zip a working_dir once and cache by content hash (reference:
        runtime_env packaging.py — zip -> GCS KV -> workers download).
        Workers fetch it over their connection via get_package."""
        import hashlib
        import io
        import zipfile

        path = os.path.abspath(path)
        with self.lock:
            cache = getattr(self, "_pkg_cache", None)
            if cache is None:
                cache = self._pkg_cache = {}      # pkg_id -> zip bytes
                self._pkg_by_path = {}            # path -> (stamp, pkg_id)
            ent = self._pkg_by_path.get(path)
        # Validity stamp covers mtimes AND the file-name set, so deleted
        # files invalidate the cache too.
        names = sorted(os.path.relpath(os.path.join(r, f), path)
                       for r, _d, fs in os.walk(path) for f in fs)
        mtime = max((os.path.getmtime(os.path.join(path, n))
                     for n in names), default=os.path.getmtime(path))
        stamp = (mtime, hashlib.sha1(
            "\0".join(names).encode()).hexdigest())
        if ent is not None and ent[0] == stamp:
            return ent[1]
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for n in names:
                z.write(os.path.join(path, n), n)
        blob = buf.getvalue()
        pkg_id = hashlib.sha1(blob).hexdigest()[:16]
        with self.lock:
            if ent is not None and ent[1] != pkg_id:
                # Superseded version: drop its zip unless another path
                # still maps to it (head memory must not grow per edit).
                old = ent[1]
                if not any(v[1] == old for k, v in
                           self._pkg_by_path.items() if k != path):
                    self._pkg_cache.pop(old, None)
            self._pkg_cache[pkg_id] = blob
            self._pkg_by_path[path] = (stamp, pkg_id)
        return pkg_id

    def _lease_worker_locked(self, node: NodeState, rec: TaskRecord,
                             tpu_chips) -> WorkerHandle:
        env_key = self._env_key_for(rec, tpu_chips)
        idle = node.idle_workers.get(env_key)
        if idle:
            w = idle.pop()
            return w
        return self._spawn_worker(node, env_key, rec, tpu_chips)

    def _worker_config_env(self) -> Dict[str, str]:
        """Config knobs that follow _system_config overrides into workers
        via the env namespace (worker GLOBAL_CONFIG is rebuilt from env at
        import).  Shared by both spawn paths so a knob added here reaches
        agent-spawned workers too — the ray_tpu.data entries are what lets
        a Dataset consumed INSIDE a worker (the Train shard contract) see
        the driver's engine switch and byte budget."""
        return {
            "RAY_TPU_MAX_INLINE": str(self.config.max_inline_object_size),
            "RAY_TPU_POOL_BYTES": str(self.config.shm_pool_bytes),
            "RAY_TPU_OBJECT_POOL_SIZE": str(self.config.object_pool_size),
            "RAY_TPU_OBJECT_STRIPE_THRESHOLD":
                str(self.config.object_stripe_threshold),
            "RAY_TPU_DIRECT_PUTS":
                "1" if self.config.direct_puts else "0",
            "RAY_TPU_OBJECT_PUT_STRIPE_THRESHOLD":
                str(self.config.object_put_stripe_threshold),
            "RAY_TPU_OBJECT_PUT_POOL_SIZE":
                str(self.config.object_put_pool_size),
            "RAY_TPU_ARG_PREFETCH_DEPTH":
                str(self.config.arg_prefetch_depth),
            "RAY_TPU_STREAMING_EXECUTOR":
                "1" if self.config.streaming_executor else "0",
            "RAY_TPU_DATA_MEMORY_BUDGET":
                str(self.config.data_memory_budget),
            "RAY_TPU_DATA_MEMORY_BUDGET_FRACTION":
                str(self.config.data_memory_budget_fraction),
            "RAY_TPU_DATA_MAX_INFLIGHT_TASKS":
                str(self.config.data_max_inflight_tasks),
            # Push-shuffle knobs: the switch and both tuning knobs are
            # read in the WORKER process (map tasks partition + push,
            # reducer actors merge on arrival), and a Dataset consumed
            # inside a worker plans its shuffle there too.
            "RAY_TPU_PUSH_SHUFFLE":
                "1" if self.config.push_shuffle else "0",
            "RAY_TPU_SHUFFLE_PARTITION_BYTES_TARGET":
                str(self.config.shuffle_partition_bytes_target),
            "RAY_TPU_SHUFFLE_MERGE_FANIN":
                str(self.config.shuffle_merge_fanin),
            # Distributed-training knobs: the switch and both tuning
            # knobs are read wherever the trainer/learner runs — stage
            # actors push in WORKER processes, and a PipelineTrainer or
            # Impala built inside a Trainable worker must see the
            # driver's _system_config.
            "RAY_TPU_DISTRIBUTED_TRAINING":
                "1" if self.config.distributed_training else "0",
            "RAY_TPU_PIPELINE_MICROBATCHES":
                str(self.config.pipeline_microbatches),
            "RAY_TPU_IMPALA_QUEUE_DEPTH":
                str(self.config.impala_queue_depth),
            "RAY_TPU_DECENTRALIZED_DISPATCH":
                "1" if self.config.decentralized_dispatch else "0",
            "RAY_TPU_LEASE_SLOTS": str(self.config.lease_slots),
            "RAY_TPU_LEASE_TTL_S": str(self.config.lease_ttl_s),
            "RAY_TPU_LEASE_RENEW_TASKS":
                str(self.config.lease_renew_tasks),
            "RAY_TPU_LEASE_SPILLBACK_DEPTH":
                str(self.config.lease_spillback_depth),
            # Serving knobs: the continuous-batching switch is read in
            # the REPLICA worker, the autoscale windows in the
            # controller worker — both only see _system_config through
            # this env namespace.
            "RAY_TPU_CONTINUOUS_BATCHING":
                "1" if self.config.continuous_batching else "0",
            # Serving memory plane: all three are read in the REPLICA
            # worker (paged admission + prefix reuse + draft length).
            "RAY_TPU_PAGED_KV":
                "1" if self.config.paged_kv else "0",
            "RAY_TPU_PREFIX_CACHING":
                "1" if self.config.prefix_caching else "0",
            "RAY_TPU_SPECULATIVE_K": str(self.config.speculative_k),
            "RAY_TPU_SERVE_METRIC_LOOKBACK_S":
                str(self.config.serve_metric_lookback_s),
            "RAY_TPU_SERVE_DOWNSCALE_DELAY_S":
                str(self.config.serve_downscale_delay_s),
            # Disaggregated serving: the split switch is read by the
            # controller (pool twin deploys), replicas (prefill-only /
            # chain-import step paths) and handles/proxies (affinity
            # routing); the stripe threshold wherever a prefill replica
            # pushes a chain.
            "RAY_TPU_DISAGGREGATED_SERVING":
                "1" if self.config.disaggregated_serving else "0",
            "RAY_TPU_KV_STREAM_STRIPE_THRESHOLD":
                str(self.config.kv_stream_stripe_threshold),
            "RAY_TPU_PREFIX_AFFINITY":
                "1" if self.config.prefix_affinity else "0",
            # Fault-tolerance knobs: workers keep their own bounded
            # lineage for direct-path tasks and arm actor checkpoint
            # hooks — both must see the driver's _system_config.
            "RAY_TPU_RECOVERY": "1" if self.config.recovery else "0",
            # The legacy lineage escape hatch gates every DirectCaller's
            # worker-side table exactly like the head's — a driver
            # turning it off via _system_config must reach them (found
            # by protocheck RTL504: the knob was read in workers but
            # plumbed to neither spawn path).
            "RAY_TPU_LINEAGE_ENABLED":
                "1" if self.config.lineage_enabled else "0",
            "RAY_TPU_LINEAGE_BYTES_BUDGET":
                str(self.config.lineage_bytes_budget),
            "RAY_TPU_ACTOR_CHECKPOINT_INTERVAL_S":
                str(self.config.actor_checkpoint_interval_s),
            # Elastic-pod knobs: the drain switch/deadline also reach
            # node agents via the agent_ack config dict (their env wins
            # per node); riding the worker env keeps the whole cluster
            # on the driver's _system_config.
            "RAY_TPU_ELASTIC_DRAIN":
                "1" if self.config.elastic_drain else "0",
            "RAY_TPU_DRAIN_DEADLINE_S":
                str(self.config.drain_deadline_s),
            "RAY_TPU_DRAIN_MIGRATE_MAX_BYTES":
                str(self.config.drain_migrate_max_bytes),
            "RAY_TPU_SPOT_FALLBACK_THRESHOLD":
                str(self.config.spot_fallback_threshold),
            # Head-failover knobs: workers park + re-dial + re-register
            # across a head restart (the switch and both windows are
            # read in the worker process).
            "RAY_TPU_HEAD_FAILOVER":
                "1" if self.config.head_failover else "0",
            "RAY_TPU_HEAD_RECONNECT_GRACE_S":
                str(self.config.head_reconnect_grace_s),
            "RAY_TPU_HEAD_REREGISTER_TIMEOUT_S":
                str(self.config.head_reregister_timeout_s),
            # Failure-detection knobs (gray failures): workers read the
            # master switch, the wire deadlines/retries, and the
            # heartbeat period; the head-side suspicion knobs ride too
            # so a worker-spawned subprocess that becomes a driver sees
            # one coherent config.
            "RAY_TPU_FAILURE_DETECTION":
                "1" if self.config.failure_detection else "0",
            "RAY_TPU_NET_STALL_TIMEOUT_S":
                str(self.config.net_stall_timeout_s),
            "RAY_TPU_NET_CONNECT_TIMEOUT_S":
                str(self.config.net_connect_timeout_s),
            "RAY_TPU_NET_RETRY_COUNT":
                str(self.config.net_retry_count),
            "RAY_TPU_NET_RETRY_BACKOFF_BASE_MS":
                str(self.config.net_retry_backoff_base_ms),
            "RAY_TPU_HEALTH_CHECK_PERIOD_S":
                str(self.config.health_check_period_s),
            "RAY_TPU_HEALTH_CHECK_TIMEOUT_S":
                str(self.config.health_check_timeout_s),
            "RAY_TPU_HEALTH_CHECK_FAILURE_THRESHOLD":
                str(self.config.health_check_failure_threshold),
            "RAY_TPU_HEALTH_CHECK_INITIAL_DELAY_S":
                str(self.config.health_check_initial_delay_s),
        }

    def _spawn_worker(self, node: NodeState, env_key: str,
                      rec: Optional[TaskRecord], tpu_chips) -> WorkerHandle:
        import subprocess
        import sys

        worker_id = WorkerID.from_random()
        if node.agent is not None:
            return self._spawn_worker_via_agent(node, env_key, rec,
                                                tpu_chips, worker_id)
        env = dict(os.environ)
        if rec is not None:
            renv = rec.spec.get("runtime_env") or {}
            env.update(renv.get("env_vars", {}))
            if renv.get("working_dir"):
                env["RAY_TPU_WORKING_DIR_PKG"] = \
                    self._package_working_dir(renv["working_dir"])
            if renv.get("pip"):
                # Worker builds/reuses the requirements venv and
                # re-execs under it (runtime_env_pip.py).
                import json as _json

                env["RAY_TPU_PIP_SPEC"] = _json.dumps(renv["pip"])
        if tpu_chips:
            env["TPU_VISIBLE_CHIPS"] = ",".join(map(str, tpu_chips))
            env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"1,1,{len(tpu_chips)}"
            env.pop("JAX_PLATFORMS", None)
        else:
            # CPU-only workers must not grab the TPU runtime — and must not
            # pay the TPU-plugin import at interpreter startup either.
            # Hard override (not setdefault): the driver may itself run
            # under JAX_PLATFORMS=axon/tpu, which would crash in a worker
            # whose tunnel env is stripped below.
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env.pop("TPU_VISIBLE_CHIPS", None)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        # Workers must import what the driver can: cloudpickle serializes
        # module-level functions by reference, so the driver's sys.path
        # (minus interpreter-internal entries) rides along (the reference's
        # workers likewise inherit the job's environment/working dir).
        import sys as _sys
        extra = [p for p in _sys.path
                 if p and p not in (pkg_root,) and os.path.isdir(p)]
        env["PYTHONPATH"] = os.pathsep.join(
            [pkg_root] + extra + ([env["PYTHONPATH"]]
                                  if env.get("PYTHONPATH") else []))
        env.update(self._worker_config_env())
        env.update({
            "RAY_TPU_WORKER_ID": worker_id.hex(),
            "RAY_TPU_ENV_KEY": env_key,
            "RAY_TPU_ADDRESS": self._listener.address,
            "RAY_TPU_AUTHKEY": self._authkey.hex(),
            "RAY_TPU_SESSION": self.session_id,
            "RAY_TPU_SHM_DIR_OVERRIDE": self.shm._dir,
            "RAY_TPU_NODE_ID": node.node_id.hex(),
            "RAY_TPU_JOB_ID": self.job_id.hex(),
            # Per-process slice of the node store cap + the shared spill
            # dir (per-node spilling; local_object_manager.h:41).
            "RAY_TPU_STORE_BYTES": str(self.config.object_store_memory),
            "RAY_TPU_SPILL_DIR_OVERRIDE": self.spill_dir,
        })
        env["RAY_TPU_STORE_ID"] = self.store_id
        # Worker output goes to a per-worker file (reference: workers log
        # under the session dir; log_monitor.py tails them to the
        # driver).  The head's monitor thread re-prints new lines with a
        # worker prefix when log_to_driver is on.
        log_dir = os.path.join(self._sock_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_f = open(os.path.join(log_dir, f"worker-{worker_id.hex()}.log"),
                     "ab", buffering=0)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_main"],
            env=env, cwd=pkg_root, stdout=log_f,
            stderr=subprocess.STDOUT)
        log_f.close()  # the child holds its own fd
        w = WorkerHandle(worker_id, None, proc, node, env_key, tpu_chips)
        node.all_workers[id(w)] = w
        self._pending_workers[worker_id.hex()] = w
        return w

    def _spawn_worker_via_agent(self, node: NodeState, env_key: str,
                                rec, tpu_chips, worker_id) -> WorkerHandle:
        """Lease a worker on an out-of-process node: the agent forks it
        there; the worker dials our TCP listener directly (reference:
        raylet WorkerPool::StartWorkerProcess, worker_pool.h:156)."""
        overrides = {}
        if rec is not None:
            renv = rec.spec.get("runtime_env") or {}
            overrides.update(renv.get("env_vars", {}))
            if renv.get("working_dir"):
                overrides["RAY_TPU_WORKING_DIR_PKG"] = \
                    self._package_working_dir(renv["working_dir"])
            if renv.get("pip"):
                import json as _json

                overrides["RAY_TPU_PIP_SPEC"] = _json.dumps(renv["pip"])
        if tpu_chips:
            overrides["TPU_VISIBLE_CHIPS"] = ",".join(map(str, tpu_chips))
            overrides["TPU_CHIPS_PER_PROCESS_BOUNDS"] = \
                f"1,1,{len(tpu_chips)}"
        else:
            overrides["JAX_PLATFORMS"] = "cpu"
        overrides.update(self._worker_config_env())
        overrides.update({
            "RAY_TPU_WORKER_ID": worker_id.hex(),
            "RAY_TPU_ENV_KEY": env_key,
            "RAY_TPU_ADDRESS": self.tcp_address,
            "RAY_TPU_AUTHKEY": self._authkey.hex(),
            "RAY_TPU_SESSION": self.session_id,
            "RAY_TPU_NODE_ID": node.node_id.hex(),
            "RAY_TPU_JOB_ID": self.job_id.hex(),
        })
        w = WorkerHandle(worker_id, None, None, node, env_key, tpu_chips)
        node.all_workers[id(w)] = w
        self._pending_workers[worker_id.hex()] = w
        node.agent.send(("spawn_worker", worker_id.hex(), overrides))  # noqa: RTL604 -- spawn is a rare, already process-fork-slow path; one small control frame
        return w

    def _object_server_loop(self):
        """The head's object server: same shared accept loop the node
        agents run, serving segments from the head's own store."""
        object_transfer.accept_loop(self._obj_listener, self.shm,
                                    lambda: self._stopped,
                                    "ray_tpu-objconn")

    def _adv_caps(self, caps) -> tuple:
        """Advertised object-server verbs, with the put verbs withheld
        while ``direct_puts`` is off — pushers are capability-gated, so
        not advertising IS the off switch (the legacy put_parts path,
        byte-identical, every direct-put counter zero)."""
        caps = tuple(caps or ())
        if self.config.direct_puts:
            return caps
        return tuple(c for c in caps
                     if c not in object_transfer.PUT_CAPS)

    def _accept_loop(self, listener):
        while not self._stopped:
            try:
                conn = listener.accept()
                protocol.enable_nodelay(conn)
            except (OSError, EOFError, multiprocessing.AuthenticationError):
                if self._stopped:
                    return
                continue
            try:
                msg = protocol.recv(conn)
            except (EOFError, OSError):
                continue
            # Admission waits for __init__ (incl. snapshot restore) to
            # finish: reconnecting peers race a restarting head's boot.
            self._boot_ready.wait(timeout=60)
            if msg[0] == "agent_ready":
                self._register_agent(conn, msg[1])
                continue
            if msg[0] == "reregister":
                # A surviving worker of the previous head incarnation
                # re-dialed after our restart: re-admit it under its old
                # identity and reconcile what it re-advertises (held
                # leases, queued/running tasks, owned objects, its actor
                # incarnation).  Reference: workers reconnecting across
                # GCS restart, gcs_failover_worker_reconnect_timeout.
                self._handle_worker_reregister(conn, msg[1])
                continue
            if msg[0] == "client_ready":
                # External process attaching in client mode (reference:
                # Ray Client, python/ray/util/client/) — a worker-protocol
                # connection that never takes a lease.
                w = WorkerHandle(WorkerID.from_random(), None, None,
                                 self.head_node, "client", [])
                w.attach(conn)
                w.ready.set()
                with self.lock:
                    self._conn_to_worker[conn] = w
                # The ack's info dict is the client's direct-put
                # bootstrap: with the head's store identity + object-
                # server address + advertised verbs, a large client put
                # streams straight into the head's store over the data
                # plane.  Old clients ignore the extra element; a new
                # client against an old (2-tuple-ack) head keeps the
                # legacy put_parts path.
                protocol.send(conn, ("client_ack", self.session_id, {
                    "store_id": self.store_id,
                    "object_addr": self.object_addr,
                    "object_caps": list(self._adv_caps(
                        object_transfer.CAPS)),
                }))
                threading.Thread(target=self._worker_reader,
                                 args=(conn, w), daemon=True,
                                 name="ray_tpu-rx-client").start()
                continue
            if msg[0] != "ready":
                conn.close()
                continue
            worker_id_hex = msg[1]
            with self.lock:
                w = self._pending_workers.pop(worker_id_hex, None)
                if w is None or w.dead:
                    conn.close()
                    continue
                if len(msg) > 3:
                    w.direct_addr = msg[3]
                # Spawned by this head: same build, speaks the lease
                # plane (unsolicited grants included).
                w.lease_caps = True
                # First suspicion deadline gets the initial-delay slack
                # (boot/env/JIT warmup legitimately delay heartbeats).
                w.last_seen = (time.monotonic()
                               + self.config.health_check_initial_delay_s)
                self._conn_to_worker[conn] = w
                self._workers_by_hex[worker_id_hex] = w
            # Attach OUTSIDE the runtime lock: the outbox flush is a
            # blocking socket write, and holding the big lock across it
            # stalled every other acquirer on one slow worker conn
            # (found by lockgraph RTL604).  Sends racing the attach just
            # park in the outbox under send_lock — order is preserved.
            try:
                w.attach(conn)
            except Exception:
                self._on_worker_death(w)
                continue
            w.ready.set()
            # One reader thread per connection (replaces the old select
            # loop): recv/unpickle for different workers runs in parallel,
            # and a burst from one worker is drained back-to-back instead
            # of one message per poll cycle.
            threading.Thread(target=self._worker_reader, args=(conn, w),
                             daemon=True, name="ray_tpu-rx").start()

    def _register_agent(self, conn, info: dict):
        """A node agent dialed in: add its node to the cluster (reference:
        NodeManager::RegisterGcs, gcs_node_manager.h:41 HandleRegisterNode).
        """
        agent = AgentHandle(conn, info["store_id"], info["shm_dir"], info)
        agent.last_seen = (time.monotonic()
                           + self.config.health_check_initial_delay_s)
        resources = dict(info.get("resources") or {"CPU": 1.0})
        resources.setdefault("memory", float(2 ** 33))
        with self.lock:
            node = None
            if info.get("reconnect"):
                # Agent of a previous head incarnation re-dialing after
                # our restart: re-claim its restored node under the OLD
                # id so its surviving workers' node identity stays
                # valid.  available is NOT reset — adopted actors may
                # have acquired their slots before the agent returned.
                self._awaiting_nodes.pop(info["store_id"], None)
                for cand in self.nodes.values():
                    if cand.store_id == info["store_id"] \
                            and cand.agent is None \
                            and cand is not self.head_node:
                        node = cand
                        break
                if node is not None:
                    node.alive = True
                    node.agent = agent
                    self.reconnected_nodes += 1
            if node is None:
                node = self._add_node_locked(resources,
                                             labels=info.get("labels"),
                                             agent=agent,
                                             store_id=info["store_id"])
            agent.node = node
            self._agents[agent.store_id] = agent
            self._conn_to_agent[conn] = agent
            # Ack INSIDE the lock: the moment the node is registered, any
            # thread holding the lock may dispatch a spawn_worker to this
            # agent — the ack must be first on the wire (the agent's
            # handshake asserts it).
            # The ack carries head config the agent must mirror (the
            # memory monitor's knobs — _system_config applies cluster-
            # wide, not just to the head's own sampler).
            agent.send(  # noqa: RTL402 -- one-time handshake; the ack must beat any locked spawn_worker onto this conn
                ("agent_ack", node.node_id.hex(), self.session_id,
                 {"memory_monitor_threshold":
                      self.config.memory_monitor_threshold,
                  "memory_monitor_interval_s":
                      self.config.memory_monitor_interval_s,
                  "memory_monitor_test_file":
                      self.config.memory_monitor_test_file,
                  # Failover knobs the agent mirrors (its own env wins
                  # when explicitly set — the per-node escape hatch):
                  # keep-workers vs legacy teardown on head EOF, and
                  # the re-dial grace window.
                  "head_failover": self.config.head_failover,
                  "head_reconnect_grace_s":
                      self.config.head_reconnect_grace_s,
                  "agent_reconnect": self.config.agent_reconnect,
                  # Elastic pods: the drain verbs this head understands
                  # (the agent gates preempt_notice on membership — an
                  # old head is never probed) plus the knobs the agent
                  # mirrors for its self-drain deadline.
                  "drain_caps": (["preempt_notice", "drain_node"]
                                 if self.config.elastic_drain else []),
                  "elastic_drain": self.config.elastic_drain,
                  "drain_deadline_s": self.config.drain_deadline_s,
                  # Failure detection: the agent mirrors the master
                  # switch and heartbeat cadence (its env wins per
                  # node) so an off-switch cluster sends zero
                  # heartbeats and a tuned period applies everywhere.
                  "failure_detection": self.config.failure_detection,
                  "health_check_period_s":
                      self.config.health_check_period_s}))
        threading.Thread(target=self._agent_reader, args=(conn, agent),
                         daemon=True, name="ray_tpu-rx-agent").start()
        with self.lock:
            self._dispatch_locked()

    def _handle_worker_reregister(self, conn, info: dict):
        """A worker process that survived the previous head's death
        re-dialed: re-admit it under its OLD identity (worker id, node,
        env key — the process, its store segments, and its direct-push
        endpoint are all still live) and reconcile its claims."""
        worker_hex = info.get("worker_id", "")
        node_hex = info.get("node_id", "")
        with self.lock:
            node = self._node_by_hex_locked(node_hex)
            refused = node is None or not self.config.head_failover
        if refused:
            # Unknown node (fresh head, no snapshot) or duplicate:
            # refuse — the worker exits, which is the pre-failover
            # behavior and the correct one for a cluster that did not
            # restore.  (Outside the lock: nobody holds this conn yet.)
            try:
                protocol.send(conn, ("reregister_nack",))
            except Exception:
                pass
            try:
                conn.close()
            except Exception:
                pass
            return
        try:
            w = WorkerHandle(
                WorkerID(bytes.fromhex(worker_hex)), None, None,
                node, info.get("env_key") or "default",
                list(info.get("tpu_chips") or []))
        except ValueError:
            try:
                conn.close()
            except Exception:
                pass
            return
        w.lease_caps = True
        if info.get("direct_addr"):
            w.direct_addr = info["direct_addr"]
        with self.lock:
            stale = self._workers_by_hex.get(worker_hex)
            if stale is not None and not stale.dead:
                # The SAME process re-dialing again: its previous
                # reregister was accepted but the ack never arrived
                # (conn broke in the window).  The retry supersedes the
                # stale handle — nacking it would exit a live worker
                # the head believes it adopted.  Detach the stale handle
                # without the death path (nothing died), transfer its
                # claims, and re-park its actor for re-adoption below.
                stale.dead = True
                self._conn_to_worker.pop(stale.conn, None)
                stale.node.all_workers.pop(id(stale), None)
                for lst in stale.node.idle_workers.values():
                    if stale in lst:
                        lst.remove(stale)
                try:
                    stale.conn.close()
                except Exception:
                    pass
                if stale.lease_req is not None and not stale.released:
                    stale.node.release(stale.lease_req)
                stale.lease_req = None
                if stale.actor_id is not None:
                    actor = self.actors.get(stale.actor_id)
                    if actor is not None and actor.worker is stale:
                        actor.worker = None
                        actor.status = RESTARTING
                        self._restored_actors.setdefault(
                            stale.actor_id, {})
                for tid_bin, rec in stale.inflight.items():
                    rec.worker = w
                    w.inflight[tid_bin] = rec
                stale.inflight.clear()
            # Ack straight on the conn BEFORE attach: registration under
            # the lock means another thread may send through the handle
            # the moment it lands in the tables — the ack must be first
            # on the wire (the worker recv()s it inline).
            try:
                protocol.send(conn, ("reregister_ack", self.session_id))  # noqa: RTL402 -- one-time handshake; the ack must beat any locked send onto this conn
            except Exception:
                return
            w.attach(conn)
            w.ready.set()
            self._conn_to_worker[conn] = w
            self._workers_by_hex[worker_hex] = w
            node.all_workers[id(w)] = w
            self.reregistered_workers += 1
            self._apply_reregister_claims_locked(w, info)
            if not w.inflight and w.actor_id is None \
                    and w.client_lease is None:
                w.idle_since = time.monotonic()
                node.idle_workers.setdefault(w.env_key, []).append(w)
        threading.Thread(target=self._worker_reader, args=(conn, w),
                         daemon=True, name="ray_tpu-rx").start()
        with self.lock:
            self._dispatch_locked()

    def _apply_reregister_claims_locked(self, w: WorkerHandle,
                                        info: dict):
        """Reconcile one re-registration's claims against the restored
        tables: the actor incarnation it hosts, the owned objects it
        re-advertises, its queued/running head-dispatched tasks, and the
        peer leases it holds."""
        aid = info.get("actor_id")
        if aid:
            actor = self.actors.get(aid)
            # Adoption only while the actor is still PARKED: once a cold
            # restore claimed it (popped from _restored_actors), this
            # surviving incarnation is stale — adopting it too would
            # split the actor across two workers.
            if actor is not None and aid in self._restored_actors \
                    and actor.worker is None and actor.status != DEAD:
                # Adoption: the incarnation (and its in-memory state)
                # survived — no __init__ re-run, no checkpoint restore.
                actor.status = ALIVE
                actor.worker = w
                actor.node = w.node
                w.actor_id = aid
                req = actor.options.get("resources") or {"CPU": 1.0}
                w.lease_req = dict(req)
                w.node.acquire(req)
                if not actor.created_future.done():
                    actor.created_future.set_result(True)
                self._restored_actors.pop(aid, None)
                self.adopted_actors += 1
                self._gcs_dirty += 1
                self._pump_actor_locked(actor)
            elif actor is None:
                # Created after the last snapshot: adopt a minimal
                # record so addressing/kill/death paths keep working.
                actor = ActorState(aid)
                actor.status = ALIVE
                actor.worker = w
                actor.node = w.node
                req = dict(info.get("resources") or {"CPU": 1.0})
                actor.options = {"resources": req}
                actor.created_future.set_result(True)
                self.actors[aid] = actor
                w.actor_id = aid
                w.lease_req = dict(req)
                w.node.acquire(req)
                self.adopted_actors += 1
        for item in info.get("objects", ()):
            b, ok, descr, nested = item[0], item[1], item[2], item[3]
            oid = ObjectID(b)
            st = self.objects.get(oid)
            if st is None:
                st = self.objects[oid] = ObjectState()
                st.pins = 1        # failover pin (restore semantics)
                st.worker_refs = 1  # the exporter's aggregate ref
            if st.status == PENDING and descr is not None:
                self._complete_object_locked(oid, descr, bool(ok))
            st.shipped = True
        for t in info.get("tasks", ()):
            tid_bin, num_returns, is_actor_task = t[0], t[1], t[2]
            if tid_bin in self.tasks:
                continue
            spec = {"task_id": tid_bin, "num_returns": num_returns,
                    "name": "failover_readopted", "resources": {},
                    "args": [], "kwargs": {}}
            rec = TaskRecord(spec, {}, 0)
            rec.dispatched = True
            rec.worker = w
            rec.node = w.node
            tid = TaskID(tid_bin)
            for i in range(num_returns):
                oid = tid.object_id(i)
                if oid not in self.objects:
                    self.objects[oid] = ObjectState(tid)
            self.tasks[tid_bin] = rec
            if is_actor_task and w.actor_id is not None:
                actor = self.actors.get(w.actor_id)
                if actor is not None:
                    rec.actor_id = w.actor_id
                    actor.inflight[tid_bin] = rec
            else:
                w.inflight[tid_bin] = rec
        restored_req = {row[0]: row[2] for row in self._restored_leases}
        now = time.monotonic()
        ttl = self.config.lease_ttl_s
        for wid in info.get("held_leases", ()):
            lw = self._workers_by_hex.get(wid)
            req = restored_req.get(wid) or {"CPU": 1.0}
            if lw is not None and not lw.dead \
                    and lw.client_lease is None and lw.actor_id is None:
                lw.client_lease = w
                lw.lease_req = dict(req)
                lw.node.acquire(lw.lease_req)
                lw.lease_expiry = (now + ttl) if ttl > 0 else None
                # A worker that re-registered before its holder was
                # pooled as idle; a leased worker must not be double-
                # booked by head dispatch (the normal grant path pops
                # it out of idle the same way).
                for lst in lw.node.idle_workers.values():
                    if lw in lst:
                        lst.remove(lw)
            else:
                # The leased worker hasn't re-registered yet: park the
                # claim; its own reregister consumes it below.
                self._pending_lease_claims[wid] = (w.worker_id.hex(),
                                                   req)
        claim = self._pending_lease_claims.pop(
            w.worker_id.hex(), None)
        if claim is not None and w.actor_id is None and not w.inflight \
                and w.client_lease is None:
            holder = self._workers_by_hex.get(claim[0])
            if holder is not None and not holder.dead:
                w.client_lease = holder
                w.lease_req = dict(claim[1] or {"CPU": 1.0})
                w.node.acquire(w.lease_req)
                w.lease_expiry = (now + ttl) if ttl > 0 else None

    # How long an unfulfillable client lease request is parked at the head
    # before an empty grant is returned (the caller then falls back to the
    # head path for a bounded chunk and re-requests).  The reference's
    # raylet queues RequestWorkerLease indefinitely; we bound it so a
    # zero-capacity cluster still makes progress via the head scheduler.
    CLIENT_LEASE_PARK_S = 1.0

    def _grant_client_leases(self, lessee: WorkerHandle, rid,
                             resources: Dict[str, float], n: int,
                             opts: Optional[dict] = None):
        """Lease up to ``n`` workers to a peer caller for direct task
        push.  The head acquires node resources (exactly like a dispatch
        lease) but never sees the tasks; the caller returns the lease via
        ("lease_return", ...) or by dying (reference: raylet
        RequestWorkerLease / ReturnWorker).

        ``opts`` (lease-plane capability gate): {"v": 1} selects the
        dict-shaped reply carrying per-worker node ids, the granted slot
        count, the TTL the holder must renew within, and a next-best-node
        hint; {"hint": node_hex} steers the grant toward that node (the
        spillback hint round-tripping back, reference hybrid policy).
        Absent/None keeps the legacy bare-list reply.

        Zero-grant requests are PARKED, not refused: the request waits
        (bounded) for resources to free, exactly like the raylet's lease
        queue — an immediate empty reply made every concurrent caller dump
        its whole queue on the head the moment leases momentarily ran out,
        which is what collapsed multi-client task throughput."""
        recovery.syncpoint("lease_grant")
        req = {k: float(v) for k, v in resources.items()}
        with self.lock:
            granted = self._try_client_grant_locked(
                lessee, req, n, hint=(opts or {}).get("hint"))
            if not granted:
                park = {"lessee": lessee, "rid": rid, "req": req, "n": n,
                        "opts": opts,
                        "deadline": time.monotonic()
                        + self.CLIENT_LEASE_PARK_S}
                self._pending_client_leases.append(park)
                t = threading.Timer(self.CLIENT_LEASE_PARK_S + 0.02,
                                    self._sweep_client_leases)
                t.daemon = True
                t.start()
                return
        self._finish_client_grant(lessee, rid, granted, opts=opts)

    def _node_by_hex_locked(self, node_hex) -> Optional[NodeState]:
        if not node_hex:
            return None
        for nid in self.node_order:
            if nid.hex() == node_hex:
                return self.nodes[nid]
        return None

    def _try_client_grant_locked(self, lessee: WorkerHandle,
                                 req: Dict[str, float], n: int,
                                 hint=None) -> List[WorkerHandle]:
        hint_node = self._node_by_hex_locked(hint)
        granted: List[WorkerHandle] = []
        for _ in range(max(1, n)):
            pseudo = TaskRecord(
                {"resources": req, "num_returns": 0,
                 "name": "client_lease", "task_id": b""}, req, 0)
            if hint_node is not None and hint_node.alive \
                    and hint_node.can_fit(req):
                # Spillback hint: the holder just bounced off an
                # oversubscribed node — place the replacement lease where
                # the head said the capacity was.
                node = hint_node
            else:
                node = self._pick_node_locked(pseudo)
            if node is None:
                # Client leases are transient: blocked workers (usually
                # the requesting clients themselves, parked in ray.get)
                # lend their slots here too.
                node = self._lend_node_locked(pseudo)
            if node is None:
                break
            node.acquire(req)
            pseudo.node = node
            w = self._lease_worker_locked(node, pseudo, [])
            w.lease_req = dict(req)
            w.client_lease = lessee
            granted.append(w)
        return granted

    def _spill_hint_locked(self, req: Dict[str, float],
                           granted: List[WorkerHandle]) -> Optional[str]:
        """Next-best node for this class BESIDES the ones just granted on
        — shipped with the grant so a holder bouncing off an
        oversubscribed worker knows where to ask next (the reference
        hybrid policy's spillback target)."""
        used = {id(w.node) for w in granted}
        for nid in self.node_order:
            node = self.nodes[nid]
            if node.alive and id(node) not in used and node.can_fit(req):
                return node.node_id.hex()
        return None

    def _service_client_leases_locked(self):
        """Try parked client lease requests against freed capacity; called
        from _dispatch_locked (which runs on every resource release).
        Successful grants finish on a thread (they wait for worker spawn);
        expired requests get their empty reply so the caller can fall
        back."""
        if not self._pending_client_leases:
            return
        now = time.monotonic()
        still: deque = deque()
        while self._pending_client_leases:
            p = self._pending_client_leases.popleft()
            if p["lessee"].dead:
                continue
            opts = p.get("opts")
            granted = self._try_client_grant_locked(
                p["lessee"], p["req"], p["n"],
                hint=(opts or {}).get("hint"))
            if granted:
                self._finish_client_grant(p["lessee"], p["rid"], granted,
                                          opts=opts)
            elif now >= p["deadline"]:
                empty = ({"grants": []} if opts and opts.get("v")
                         else [])
                self._queue_send(p["lessee"], ("reply", p["rid"], empty))
            else:
                still.append(p)
        self._pending_client_leases = still

    def _sweep_client_leases(self):
        with self.lock:
            self._service_client_leases_locked()

    def _finish_client_grant(self, lessee: WorkerHandle, rid,
                             granted: List[WorkerHandle],
                             opts: Optional[dict] = None,
                             klass_items=None):
        """Wait for the granted workers' handshakes off-thread, then ship
        the grant.  Three reply shapes: the legacy bare list (no opts),
        the v1 dict (opts["v"]), and — when ``rid`` is None — an
        unsolicited ("lease_grant", ...) push piggybacked on a
        head-brokered submit burst (``klass_items`` names the holder-side
        scheduling class it belongs to)."""
        v1 = bool(opts and opts.get("v")) or rid is None
        cfg = self.config
        ttl = (cfg.lease_ttl_s
               if v1 and cfg.decentralized_dispatch else 0.0)
        slots = min(cfg.lease_slots, cfg.max_tasks_in_flight_per_worker)

        def finish():
            # One shared deadline across the batch (not 15s each): a
            # stuck spawn must not serialize into minutes of stall.
            deadline = time.monotonic() + 15.0
            out, failed = [], []
            for w in granted:
                left = max(0.0, deadline - time.monotonic())
                if (w.ready.wait(timeout=left) and w.direct_addr
                        and not w.dead):
                    out.append((w.worker_id.hex(), tuple(w.direct_addr),
                                w.node.node_id.hex()))
                else:
                    failed.append(w)
            hint = None
            with self.lock:
                for w in failed:
                    w.client_lease = None
                    if not w.dead:
                        self._end_lease_locked(w)
                if failed:
                    self._dispatch_locked()
                ok = [w for w in granted if w not in failed]
                if cfg.decentralized_dispatch:
                    self.lease_grants += len(ok)
                    if ttl > 0:
                        expiry = time.monotonic() + ttl
                        for w in ok:
                            w.lease_expiry = expiry
                if v1 and ok:
                    hint = self._spill_hint_locked(ok[0].lease_req or {},
                                                   ok)
            if rid is None:
                worker_send_safe(lessee, ("lease_grant", klass_items, out,  # noqa: RTL503 -- rid-None pushes are built only by _maybe_offer_lease, which gates on worker.lease_caps; solicited grants ride the "reply" verb
                                          slots, ttl, hint))
            elif v1:
                worker_send_safe(lessee, ("reply", rid,
                                          {"grants": out, "slots": slots,
                                           "ttl": ttl, "hint": hint}))
            else:
                worker_send_safe(
                    lessee, ("reply", rid, [g[:2] for g in out]))

        threading.Thread(target=finish, daemon=True,
                         name="ray_tpu-lease-grant").start()

    # Unsolicited bulk grants: minimum direct-eligible specs in one
    # head-brokered burst before the head piggybacks a lease grant on it,
    # and the per-(lessee, class) re-offer interval.
    LEASE_OFFER_MIN = 4
    LEASE_OFFER_INTERVAL_S = 0.25

    def _maybe_offer_lease(self, worker: WorkerHandle, specs: List[dict]):
        """A worker/client just pushed a submit burst through the head.
        If the burst is full of direct-eligible work, that means its
        holder is short on leases (starvation reroute or first contact):
        grant it a bulk lease on matching execution slots NOW, piggybacked
        on this very exchange, so the NEXT burst rides the direct plane
        instead of the head (reference: the raylet granting leases from
        the queue that the spillback landed in).

        Capability-gated: offered only to peers known to handle the
        ("lease_grant", ...) verb — a peer that silently dropped it
        would leak the acquired leases (PR-3 convention: new verbs are
        never sent to a peer that would ignore them)."""
        if not self.config.decentralized_dispatch or not worker.lease_caps:
            return
        elig = [s for s in specs
                if "actor_id" not in s
                and not s.get("scheduling_strategy")
                and not s.get("runtime_env")
                # Ref-carrying specs reached the head because their refs
                # are HEAD-owned — the holder's eligible() will keep
                # routing them here regardless of leases, so granting on
                # their account would be pure worker churn.
                and not any(a and a[0] == "ref"
                            for a in s.get("args", ()))
                and not any(v and v[0] == "ref"
                            for v in (s.get("kwargs") or {}).values())
                and all(k == "CPU"
                        for k in (s.get("resources") or {"CPU": 1.0}))]
        if not elig:
            return
        # Per-class accumulation: a mixed burst must not credit the
        # first spec's class with the whole count (oversized grants for
        # one class, starvation for the rest).
        by_klass: Dict[tuple, int] = {}
        for s in elig:
            req = {k: float(v) for k, v in (s.get("resources")
                                            or {"CPU": 1.0}).items()}
            key = tuple(sorted(req.items()))
            by_klass[key] = by_klass.get(key, 0) + 1
        now = time.monotonic()
        slots = max(1, min(self.config.lease_slots,
                           self.config.max_tasks_in_flight_per_worker))
        offers = []
        with self.lock:
            for klass_items, count in by_klass.items():
                ent = worker.lease_offer_ts.get(klass_items)
                if ent is None:
                    ent = worker.lease_offer_ts[klass_items] = [0.0, 0]
                # Accumulate across bursts: a starved holder reroutes
                # specs as SINGLE ("submit", ...) messages, so the offer
                # threshold must trigger on their sum, not any one
                # message's size.  These O(1) checks run FIRST — the
                # cluster scans below are paid at most once per offer
                # interval per class, never per submit message on the
                # contended fan-in path.
                ent[1] += count
                if ent[1] < self.LEASE_OFFER_MIN \
                        or now - ent[0] < self.LEASE_OFFER_INTERVAL_S:
                    continue
                # Redundant-grant guard: a holder with a PARKED
                # lease_req is already first in line for freed capacity,
                # and one that still holds leases is not starved — an
                # unsolicited grant on top would just churn extra worker
                # processes.  Reset the accumulator: this burst is
                # already being served.
                if any(p["lessee"] is worker
                       for p in self._pending_client_leases) \
                        or any(w.client_lease is worker and not w.dead
                               for node in self.nodes.values()
                               for w in node.all_workers.values()):
                    ent[0], ent[1] = now, 0
                    continue
                n = min(8, max(1, ent[1] // slots))
                ent[0], ent[1] = now, 0
                granted = self._try_client_grant_locked(
                    worker, dict(klass_items), n)
                if granted:
                    offers.append((klass_items, granted))
        for klass_items, granted in offers:
            self._finish_client_grant(worker, None, granted,
                                      klass_items=klass_items)

    def _send_task(self, worker: WorkerHandle, rec: TaskRecord):
        # Chaos syncpoint (one global None-check when unarmed): lets the
        # harness kill a worker/agent deterministically at the n-th
        # dispatch instead of racing wall-clock timers.
        recovery.syncpoint("dispatch")
        spec = rec.spec
        # Substitute resolved dependencies with value descriptors.
        def subst(a):
            if a[0] == "ref":
                oid = ObjectID(a[1])
                st = self.objects.get(oid)
                if st is None:
                    raise exc.ObjectLostError(object_id=oid.hex(),
                                              owner="driver",
                                              phase="dispatch")
                if st.status == ERRORED:
                    return st.descr  # error propagates to the task
                st.shipped = True
                return st.descr
            return a

        try:
            args = [subst(a) for a in spec["args"]]
            kwargs = {k: subst(a) for k, a in spec.get("kwargs", {}).items()}
        except exc.ObjectLostError as e:
            self._fail_task_locked(rec, e)
            return False
        # Dependency errors: fail the task without running it (reference:
        # task_manager.cc marks children failed on dep error).
        for d in list(args) + list(kwargs.values()):
            if d is not None and d[0] == protocol.ERROR:
                self._fail_task_locked(
                    rec, serialization.loads_inline(d[1]), dispatchable=False)  # noqa: RTL604 -- inline ERROR payloads are bounded-small; no socket IO
                return False
        msg_task = {
            "task_id": spec["task_id"],
            "func_id": spec.get("func_id"),
            "args": args,
            "kwargs": kwargs,
            "num_returns": spec["num_returns"],
            "name": spec.get("name", "task"),
            "resources": rec.requirements,
        }
        if "actor_id" in spec:
            msg_task["actor_id"] = spec["actor_id"]
            msg_task["method"] = spec["method"]
        fileno = id(worker)
        sent = self.worker_funcs.setdefault(fileno, set())
        func_id = spec.get("func_id")
        if func_id and func_id not in sent:
            worker.queue_msg(("func", func_id, self.functions[func_id]))
            sent.add(func_id)
        if rec.is_actor_creation:
            actor = self.actors[rec.actor_id]
            # Restartable-actor checkpointing: the worker arms the
            # __ray_save__ hook only when recovery is on AND the actor
            # can actually restart; a retained checkpoint whose home
            # store died with its node is dropped (fresh __init__ beats
            # a restore that can only fail).
            ck = actor.checkpoint
            if ck is not None and len(ck) > 3 \
                    and self._store_is_dead(ck[3]):
                ck = None
            ck_interval = (self.config.actor_checkpoint_interval_s
                           if (self.config.recovery
                               and actor.options.get("max_restarts", 0)
                               != 0)
                           else None)
            worker.queue_msg(("create_actor", {
                "task_id": spec["task_id"],
                "actor_id": rec.actor_id,
                "func_id": func_id,
                "args": args,
                "kwargs": kwargs,
                "name": spec.get("name"),
                "resources": rec.requirements,
                "max_concurrency": actor.max_concurrency,
                "checkpoint": ck,
                "checkpoint_interval": ck_interval,
            }))
        else:
            worker.queue_msg(("exec", msg_task))
        self._mark_dirty(worker)
        self.task_events.append(
            {"task_id": spec["task_id"].hex(), "name": spec.get("name"),
             "state": "RUNNING", "time": time.time()})
        return True

    def _fail_task_locked(self, rec: TaskRecord, error: BaseException,
                          dispatchable=True):
        spec = rec.spec
        payload = serialization.dumps_inline(error)  # noqa: RTL604 -- task-failure path; error payloads are bounded-small
        tid = TaskID(spec["task_id"])
        for i in range(max(1, spec["num_returns"])):
            self._complete_object_locked(
                tid.object_id(i), (protocol.ERROR, payload), ok=False)
        self._unpin_task_deps_locked(rec)
        self.tasks.pop(spec["task_id"], None)
        self.task_events.append(
            {"task_id": spec["task_id"].hex(), "name": spec.get("name"),
             "state": "FAILED", "time": time.time()})
        if rec.is_actor_creation and rec.actor_id in self.actors:
            actor = self.actors[rec.actor_id]
            actor.status = DEAD
            actor.death_cause = error
            if not actor.created_future.done():
                actor.created_future.set_exception(error)
            self._fail_actor_queue_locked(actor, error)

    def _unpin_task_deps_locked(self, rec: TaskRecord):
        spec = rec.spec
        for slot_vals in (spec["args"], list(spec.get("kwargs", {}).values())):
            for a in slot_vals:
                if a[0] == "ref":
                    oid = ObjectID(a[1])
                    st = self.objects.get(oid)
                    if st is not None:
                        st.pins -= 1
                        self._maybe_free_locked(oid, st)
        # Nested refs and by-value arg segments are kept while lineage holds
        # the spec — re-execution needs them; _release_lineage_for_locked
        # frees them when the last return object dies.
        if spec["task_id"][:12] not in self.lineage:
            self._release_spec_resources_locked(spec)

    def _release_spec_resources_locked(self, spec: dict):
        # Refs pickled inside argument containers (pinned at submission).
        nested = spec.get("nested_refs", [])
        if nested:
            spec["nested_refs"] = []
            self._unpin_nested_locked(nested)
        # Ephemeral shm segments that carried large by-value args; created
        # by the submitter's store (driver or worker), freed there.
        creator = spec.get("_creator_worker")
        for name, size in spec.get("tmp_segments", []):
            if os.path.isabs(name):
                # A spill-file path (store was full at submission time).
                try:
                    os.unlink(name)
                except OSError:
                    pass
                continue
            if creator is not None and not creator.dead:
                # Queueing cannot fail; undeliverable frees reroute via
                # the creator's death path.
                self._queue_send(creator,
                                 ("free_segment", name, size, False))
                continue
            self.shm.unlink(name, size)
        spec["tmp_segments"] = []

    # ------------------------------------------------------------- actors --
    def create_actor(self, spec: dict, options: dict):
        actor_id = os.urandom(16)
        actor = ActorState(actor_id)
        actor.func_id = spec["func_id"]
        actor.options = options
        actor.max_concurrency = options.get("max_concurrency", 1)
        actor.restarts_left = options.get("max_restarts", 0)
        actor.name = options.get("name")
        actor.namespace = options.get("namespace", "default")
        req = spec.get("resources") or {"CPU": 1.0}
        rec = TaskRecord(spec, req, 0)
        rec.is_actor_creation = True
        rec.actor_id = actor_id
        strategy = spec.get("scheduling_strategy")
        if strategy and strategy[0] == "placement_group":
            rec.pg_id = strategy[1]
            rec.bundle_index = strategy[2]
        actor.init_args = spec["args"]
        actor.init_kwargs = spec.get("kwargs", {})
        with self.lock:
            if spec.get("func_payload") is not None:
                self.functions.setdefault(spec["func_id"],
                                          spec.pop("func_payload"))
            self._pin_nested_locked(spec.get("nested_refs", []))
            if actor.name:
                key = (actor.namespace, actor.name)
                if key in self.named_actors:
                    raise ValueError(
                        f"Actor name {actor.name!r} already taken")
                self.named_actors[key] = actor_id
            self.actors[actor_id] = actor
            self.tasks[spec["task_id"]] = rec
            self._resolve_deps_locked(rec)
            self._gcs_dirty += 1
            if rec.deps_pending == 0:
                self._enqueue_pending_locked(rec)
                self._dispatch_locked()
        return actor_id

    # --------------------------------------------- GCS snapshot/restore --
    def _gcs_snapshot_loop(self):
        while not self._stopped:
            # Wake on the stop event instead of sleeping out the full
            # interval: shutdown() writes its final snapshot and must not
            # race a stale periodic write (or wait interval_s to exit).
            if self._gcs_stop.wait(self.config.gcs_snapshot_interval_s):
                return
            if self._gcs_dirty != self._gcs_snapshotted:
                try:
                    self._snapshot_gcs()
                except Exception:
                    with self.lock:
                        self.gcs_snapshot_failures += 1
                    import traceback

                    traceback.print_exc()

    def _snapshot_gcs(self, clean: bool = False):
        """Atomically persist head metadata — the full GCS table set a
        RESUMING cluster needs (reference: redis_store_client.h:28 table
        persistence + GcsInitData load, gcs_server.h:77): KV, functions,
        jobs, the OBJECT table (descriptor + home store — shm segments in
        surviving agent stores outlive a head restart, and the adopted
        session id keeps their ``rtpu-<session>-<oid>`` names valid), the
        ACTOR table including retained ``__ray_save__`` checkpoint
        descriptors, the client-lease table, and node registrations.

        ``clean`` marks the final shutdown() snapshot: workers, agents,
        and segments are about to be torn down with the session, so a
        restore from it must NOT wait for re-registrations (nothing
        survives to re-register) — it cold-restores immediately, which
        is also what keeps the in-process snapshot->restore drill
        deterministic."""
        recovery.syncpoint("snapshot")
        with self._gcs_write_lock:
            # A periodic write that lost the race to shutdown's final
            # clean snapshot must not replace it with a stale image.
            if self._stopped and not clean:
                return
            self._snapshot_gcs_inner(clean)  # noqa: RTL505 -- _gcs_write_lock is strictly OUTER to the runtime lock (this is its only acquisition site); nothing takes it under self.lock

    # Object-row rebuild policy for huge tables: below the threshold
    # every snapshot rebuilds the rows (exact); above it the O(#objects)
    # scan under the runtime lock would stall dispatch every interval,
    # so rows are reused for up to OBJ_REUSE_SNAPSHOTS writes — restore
    # already tolerates row staleness (the blip-window grace machinery
    # covers objects newer than the snapshot).
    SNAP_OBJ_EXACT_MAX = 50_000
    SNAP_OBJ_REUSE = 5

    def _snapshot_gcs_inner(self, clean: bool):
        with self.lock:
            ver = self._gcs_dirty
            named = []
            for (ns, name), aid in self.named_actors.items():
                a = self.actors.get(aid)
                if a is None or a.status == DEAD:
                    continue
                # v1-compat list (old heads restore from it).  Only
                # inline init args ship here.
                args_ok = all(d[0] == protocol.INLINE
                              for d in (a.init_args or ()))
                kwargs_ok = all(d[0] == protocol.INLINE
                                for d in (a.init_kwargs or {}).values())
                if not (args_ok and kwargs_ok):
                    continue
                named.append({
                    "namespace": ns, "name": name,
                    "func_id": a.func_id,
                    "init_args": list(a.init_args or ()),
                    "init_kwargs": dict(a.init_kwargs or {}),
                    "options": {k: v for k, v in a.options.items()
                                if k != "scheduling_strategy"},
                })
            actors = []
            for aid, a in self.actors.items():
                if a.status == DEAD:
                    continue
                args_ok = all(
                    d[0] == protocol.INLINE for d in (a.init_args or ())
                ) and all(d[0] == protocol.INLINE
                          for d in (a.init_kwargs or {}).values())
                actors.append({
                    "actor_id": aid,
                    "name": a.name, "namespace": a.namespace,
                    "func_id": a.func_id,
                    "init_args": (list(a.init_args or ())
                                  if args_ok else None),
                    "init_kwargs": (dict(a.init_kwargs or {})
                                    if args_ok else None),
                    "options": {k: v for k, v in a.options.items()
                                if k != "scheduling_strategy"},
                    "restarts_left": a.restarts_left,
                    "checkpoint": a.checkpoint,
                    "home_store": (a.node.store_id
                                   if a.node is not None else ""),
                })
            cache = self._snap_obj_cache
            if (len(self.objects) <= self.SNAP_OBJ_EXACT_MAX or clean
                    or cache is None or cache[0] <= 0):
                objects = []
                for oid, st in self.objects.items():
                    if st.status != READY or st.descr is None:
                        continue
                    if st.descr[0] not in (protocol.INLINE, protocol.SHM,
                                           protocol.SPILLED):
                        continue
                    objects.append((oid.binary(), st.descr,
                                    list(st.nested_ids)))
                self._snap_obj_cache = [self.SNAP_OBJ_REUSE, objects]
            else:
                cache[0] -= 1
                objects = cache[1]
            nodes = []
            for node in self.nodes.values():
                if node.agent is None or not node.alive:
                    continue
                nodes.append({
                    "node_id": node.node_id.hex(),
                    "resources": dict(node.resources),
                    "labels": dict(node.labels),
                    "store_id": node.store_id,
                })
            leases = []
            for node in self.nodes.values():
                for w in node.all_workers.values():
                    if w.client_lease is not None and not w.dead:
                        leases.append((w.worker_id.hex(),
                                       w.client_lease.worker_id.hex(),
                                       dict(w.lease_req or {})))
            data = {
                "version": 2,
                "clean": bool(clean),
                "session_id": self.session_id,
                "store_id": self.store_id,
                "head_node_id": self.head_node.node_id.hex(),
                "kv": {ns: dict(tbl) for ns, tbl in self.kv.items()},
                "functions": dict(self.functions),
                "named_actors": named,
                "actors": actors,
                "objects": objects,
                "nodes": nodes,
                "leases": leases,
                "jobs": self._snapshot_jobs_locked(),
                "tcp_address": self.tcp_address,
            }
        blob = serialization.dumps_inline(data)
        path = self.config.gcs_snapshot_path
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())  # torn snapshot = unrestartable head
        os.replace(tmp, path)
        self._gcs_snapshotted = ver
        with self.lock:
            self.gcs_snapshots += 1

    def _snapshot_jobs_locked(self):
        mgr = getattr(self, "_job_manager", None)
        if mgr is not None:
            return mgr.snapshot_rows()
        # No manager instantiated (yet): carry restored rows forward so a
        # snapshot written before first job use can't wipe job history.
        return list(getattr(self, "_restored_jobs", []) or [])

    def _load_snapshot(self, path: str) -> Optional[dict]:
        try:
            with open(path, "rb") as f:
                return serialization.loads_inline(f.read())
        except Exception as e:  # noqa: BLE001
            # A corrupt snapshot must not make the head unstartable —
            # that is the exact failure this feature exists to survive.
            print(f"ray_tpu: GCS snapshot {path!r} unreadable ({e!r}); "
                  f"starting fresh")
            return None

    def _apply_restore(self, data: dict):
        """Head restart: reload the persisted tables, then RECONCILE
        against re-registrations instead of assuming the cluster died
        with the old head (reference: GcsInitData load + workers
        reconnecting across GCS restart, gcs_server.h:77).

        - Restored agent NODES come back not-alive under their old ids;
          a reconnecting agent re-claims its node by store id.  Nodes
          that miss the grace window stay dead (their objects surface as
          losses lazily, the PR 9 reconstruction candidates).
        - Restored OBJECTS come back READY with a permanent failover pin
          (pins=1): exact refcounts died with the old head, so the safe
          direction is leak-until-shutdown, never free-early.
        - Restored ACTORS wait for their surviving worker to re-claim
          the incarnation (state intact); unclaimed ones are re-created
          at the grace deadline from their creation spec, restoring the
          last ``__ray_save__`` checkpoint over ``__init__``.
        - Restored LEASES re-bind when both sides re-register; the
          remainder is revoked through the PR 6 path at the deadline.
        """
        v2 = data.get("version", 1) >= 2
        # Crash restores WAIT for surviving peers to re-register
        # (adoption beats re-creation: state continuity is free).  A
        # snapshot written by a CLEAN shutdown has nothing surviving it
        # — its session's workers/agents/segments were torn down — so
        # restore is immediate and SHM residue is skipped.  With the
        # failover switch off, re-registration is refused anyway, so
        # waiting would only delay the cold restores.
        wait = (not data.get("clean")) and self.config.head_failover
        with self.lock:
            for ns, tbl in data.get("kv", {}).items():
                self.kv.setdefault(ns, {}).update(tbl)
            self.functions.update(data.get("functions", {}))
            for oid_bin, descr, nested in data.get("objects", []):
                if data.get("clean") and descr[0] != protocol.INLINE:
                    continue  # segments died with the clean shutdown
                oid = ObjectID(oid_bin)
                if oid in self.objects:
                    continue
                st = self.objects[oid] = ObjectState()
                st.status = READY
                st.descr = descr
                st.pins = 1  # failover pin (see docstring)
                st.nested_ids = list(nested)
                st.shipped = True  # never pool a pre-blip segment
            if not data.get("clean"):
                for info in data.get("nodes", []):
                    node = self._add_node_locked(
                        info["resources"], labels=info.get("labels"),
                        agent=None, store_id=info["store_id"],
                        node_id=NodeID(bytes.fromhex(info["node_id"])))
                    node.alive = False  # until its agent re-registers
                    if wait:
                        self._awaiting_nodes[info["store_id"]] = node
            for info in data.get("actors", []):
                if data.get("clean"):
                    # The clean shutdown swept the session's segments
                    # and spill dir: a retained checkpoint descriptor
                    # points at deleted storage — drop it so the cold
                    # restore goes straight to fresh __init__ instead
                    # of a doomed __ray_restore__ attempt.
                    info = dict(info, checkpoint=None)
                self._restore_actor_locked(info)
            self._restored_leases = (list(data.get("leases", []))
                                     if wait else [])
        self._restored_jobs = data.get("jobs", [])
        if not v2:
            # v1 snapshot: no actor table — fall back to re-creating the
            # named actors from their inline creation specs.
            for info in data.get("named_actors", []):
                opts = dict(info["options"])
                opts["name"] = info["name"]
                opts["namespace"] = info["namespace"]
                try:
                    self.create_actor({
                        "task_id": new_task_id().binary(),
                        "func_id": info["func_id"],
                        "args": info["init_args"],
                        "kwargs": info["init_kwargs"],
                        "num_returns": 1,
                        "name": f"{info['name']}.__restore__",
                        "resources": (opts.get("resources")
                                      or {"CPU": 1.0}),
                    }, opts)
                except Exception as e:  # noqa: BLE001
                    print(f"ray_tpu: could not restore actor "
                          f"{info['name']!r}: {e!r}")
        if wait and v2:
            grace = self.config.head_reregister_timeout_s
            self._failover_grace_until = time.monotonic() + grace
            t = threading.Timer(grace, self._reconcile_failover)
            t.daemon = True
            t.start()
        else:
            # Nothing can (clean) or may (failover off) re-register:
            # cold-restore every parked actor right now.
            self._reconcile_actors(wait_for_adoption=False)

    def _restore_actor_locked(self, info: dict):
        """Rebuild one ActorState under its OLD id (surviving handles
        and direct actor channels keep working) in RESTARTING state,
        parked until its worker re-claims it or the grace timer re-
        creates it."""
        aid = info["actor_id"]
        actor = ActorState(aid)
        actor.func_id = info["func_id"]
        actor.options = dict(info.get("options") or {})
        actor.max_concurrency = actor.options.get("max_concurrency", 1)
        actor.restarts_left = info.get("restarts_left", 0)
        actor.name = info.get("name")
        actor.namespace = info.get("namespace", "default")
        actor.init_args = info.get("init_args")
        actor.init_kwargs = info.get("init_kwargs")
        actor.checkpoint = info.get("checkpoint")
        actor.status = RESTARTING
        actor.handle_count = 1  # conservative: a surviving handle may exist
        self.actors[aid] = actor
        if actor.name:
            self.named_actors[(actor.namespace, actor.name)] = aid
        self._restored_actors[aid] = info

    def _reconcile_failover(self):
        """Grace deadline: revoke/re-create everything no surviving peer
        re-claimed (reference: gcs_failover_worker_reconnect_timeout)."""
        lease_rows = []
        with self.lock:
            leases, self._restored_leases = self._restored_leases, []
            for worker_hex, holder_hex, req in leases:
                w = self._workers_by_hex.get(worker_hex)
                holder = self._workers_by_hex.get(holder_hex)
                if w is None or w.dead or w.client_lease is not None:
                    continue  # never re-registered, or already re-bound
                # Worker came back but its holder missed the window:
                # revoke through the PR 6 path so the slot frees.
                self.lease_revocations += 1
                lease_rows.append((w, holder))
            missed = {sid: n for sid, n in self._awaiting_nodes.items()
                      if n.agent is None}
            self._awaiting_nodes.clear()
            for node in missed.values():
                node.alive = False
            # Implicit blip-window objects still PENDING with no task to
            # produce them: fail as reconstruction candidates (recovery
            # refuses without lineage — that surfaces the honest
            # ObjectLostError instead of an eternal hang).
            for oid_bin in list(self._grace_objects):
                oid = ObjectID(oid_bin)
                st = self.objects.get(oid)
                if st is None or st.status != PENDING:
                    continue
                if self._try_recover_locked(oid):
                    continue
                err = (protocol.ERROR, serialization.dumps_inline(  # noqa: RTL402 -- cold once-per-failover path
                    exc.ObjectLostError(
                        object_id=oid.hex(), phase="head_failover")))
                self._complete_object_locked(oid, err, False)
            self._grace_objects.clear()
        for w, holder in lease_rows:
            if holder is not None and not holder.dead:
                try:
                    self._queue_send(holder, ("lease_revoke",
                                              [w.worker_id.hex()]))
                except Exception:
                    pass
        self._reconcile_actors(wait_for_adoption=False)
        with self.lock:
            self._dispatch_locked()

    def _reconcile_actors(self, wait_for_adoption: bool):
        """Re-create restored actors nobody re-claimed.  Adoption (the
        surviving worker re-registering its incarnation) always beats
        re-creation — state continuity is free — so a crash restore
        leaves parked actors alone until the grace deadline calls back
        in with ``wait_for_adoption=False``."""
        if wait_for_adoption:
            return
        with self.lock:
            todo = []
            for aid, info in list(self._restored_actors.items()):
                # Popping under the lock closes the adoption race: a
                # reregister arriving after this pass sees the actor
                # gone from _restored_actors and is refused — one
                # incarnation, never two.
                self._restored_actors.pop(aid, None)
                actor = self.actors.get(aid)
                if actor is None or actor.status != RESTARTING \
                        or actor.worker is not None:
                    continue
                todo.append((actor, info))
        for actor, info in todo:
            self._cold_restore_actor(actor, info)

    def _cold_restore_actor(self, actor: ActorState, info: dict):
        """Re-run an unclaimed restored actor's creation spec under its
        OLD id, restoring the retained ``__ray_save__`` checkpoint over
        ``__init__`` (reference: actor restart on GCS failover +
        checkpointable actors)."""
        if actor.init_args is None:
            # Non-inline creation args died with the old session and no
            # surviving worker re-claimed the incarnation: the actor is
            # honestly dead.
            err = exc.ActorDiedError(
                f"Actor {actor.actor_id.hex()} could not be restored "
                f"across the head restart (non-inline creation args and "
                f"no surviving incarnation)")
            with self.lock:
                actor.status = DEAD
                actor.death_cause = err
                self._gcs_dirty += 1
                self._fail_actor_queue_locked(actor, err)
            return
        req = actor.options.get("resources") or {"CPU": 1.0}
        spec = {
            "task_id": new_task_id().binary(),
            "func_id": actor.func_id,
            "args": actor.init_args,
            "kwargs": actor.init_kwargs or {},
            "num_returns": 1,
            "name": "actor.__failover_restore__",
            "resources": req,
        }
        rec = TaskRecord(spec, req, 0)
        rec.is_actor_creation = True
        rec.actor_id = actor.actor_id
        tid = TaskID(spec["task_id"])
        with self.lock:
            actor.restarts_left = info.get("restarts_left", 0)
            self.objects[tid.object_id(0)] = ObjectState(tid)
            self.tasks[spec["task_id"]] = rec
            self._gcs_dirty += 1
            self._enqueue_pending_locked(rec)
            self._dispatch_locked()

    def _enqueue_actor_task_nopump_locked(
            self, rec: TaskRecord) -> Optional[bytes]:
        """Queue an actor task without pumping; returns the actor id (or
        None for a dead actor) so bulk submitters can pump each distinct
        actor once per batch instead of once per call."""
        rec.actor_id = rec.spec["actor_id"]
        actor = self.actors.get(rec.actor_id)
        if actor is None or actor.status == DEAD:
            cause = actor.death_cause if actor else None
            self._fail_task_locked(rec, exc.ActorDiedError(
                f"Actor is dead: {cause}"))
            return None
        # Method calls replay across actor restarts per the ACTOR's
        # max_task_retries (0 = fail on death, the legacy default; -1 =
        # unlimited) — not the plain-task max_retries default.
        rec.retries_left = actor.options.get("max_task_retries", 0)
        actor.queue.append(rec)
        return rec.actor_id

    def _enqueue_actor_task_locked(self, rec: TaskRecord):
        aid = self._enqueue_actor_task_nopump_locked(rec)
        if aid is not None:
            self._pump_actor_locked(self.actors[aid])

    def _pump_actor_locked(self, actor: ActorState):
        if actor.status != ALIVE or actor.worker is None:
            return
        # Per-handle ordering: dispatch strictly FIFO; head-of-line waits for
        # its deps (reference: sequence numbers in
        # direct_actor_task_submitter.h:67).
        while actor.queue:
            rec = actor.queue[0]
            if rec.cancelled:
                actor.queue.popleft()
                continue
            if rec.deps_pending > 0:
                break
            actor.queue.popleft()
            rec.dispatched = True
            rec.node = actor.node
            rec.worker = actor.worker
            if self._send_task(actor.worker, rec):
                actor.inflight[rec.spec["task_id"]] = rec

    def _fail_actor_queue_locked(self, actor: ActorState,
                                 error: BaseException):
        while actor.queue:
            rec = actor.queue.popleft()
            self._fail_task_locked(rec, error)
        for rec in list(actor.inflight.values()):
            self._fail_task_locked(rec, error)
        actor.inflight.clear()

    # ------------------------------------------- actor handle refcounts --
    # Reference: actor out-of-scope GC (gcs_actor_manager.h + the core
    # worker's actor handle reference counting).  Every live handle holds
    # one count; pickling adds an in-flight count the deserialized copy
    # owns.  Zero count on an unnamed, non-detached actor schedules a
    # deferred termination check — deferred (not immediate) because an
    # in-flight +1 from another process's pickle may still be on the wire.
    _ACTOR_GC_DEFER_S = 1.0

    def actor_handle_addref(self, actor_id: bytes):
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is not None:
                actor.handle_count += 1

    def actor_handle_serialized(self, actor_id: bytes, token: bytes):
        """A pickled handle holds one count bound to ``token`` until the
        first deserialization returns it (actor.py __reduce__)."""
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return
            if token in self._actor_tokens_consumed:
                # The consume beat the create across connections: cancel
                # out without ever incrementing.
                self._actor_tokens_consumed.discard(token)
                return
            self._actor_tokens[token] = actor_id
            actor.handle_count += 1

    _TOKEN_CONSUMED_CAP = 1 << 16

    def actor_handle_deserialized(self, actor_id: bytes, token: bytes):
        with self.lock:
            aid = self._actor_tokens.pop(token, None)
            if aid is None:
                # create not seen yet (cross-conn race) — or a second+
                # materialization of the same pickle, which holds no
                # transfer count.  Only the former must be remembered.
                if len(self._actor_tokens_consumed) < \
                        self._TOKEN_CONSUMED_CAP:
                    self._actor_tokens_consumed.add(token)
                return
        self.actor_handle_decref(aid)

    def actor_handle_decref(self, actor_id: bytes):
        if self._stopped:
            return
        schedule = False
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return
            actor.handle_count -= 1
            if (actor.handle_count <= 0 and actor.name is None
                    and actor.options.get("lifetime") != "detached"
                    and actor.status != DEAD):
                schedule = True
        if schedule:
            t = threading.Timer(self._ACTOR_GC_DEFER_S,
                                self._maybe_gc_actor, args=(actor_id,))
            t.daemon = True
            t.start()

    def _maybe_gc_actor(self, actor_id: bytes):
        """Terminate an actor whose handle count stayed at zero; waits for
        queued/inflight method calls to drain first (their result refs are
        still live even though the handle is gone — the reference also
        runs outstanding work before the out-of-scope kill)."""
        if self._stopped:
            return
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is None or actor.status == DEAD:
                return
            if actor.handle_count > 0 or actor.name is not None:
                return
            if actor.inflight or actor.queue:
                busy = True
            elif actor.status == "PENDING":
                # Not yet created and nobody can reference it anymore.
                # Queued creation: fail the record now (releases its
                # pinned init-arg refs).  Dispatched creation: mark it
                # cancelled — the creation result handler reaps the
                # worker on arrival.
                busy = False
                for rec in list(self.tasks.values()):
                    if rec.is_actor_creation and rec.actor_id == actor_id:
                        rec.cancelled = True
                        if not rec.dispatched:
                            self._fail_task_locked(
                                rec, exc.ActorDiedError(
                                    "Actor went out of scope before "
                                    "creation"), dispatchable=False)
                actor.status = DEAD
                actor.death_cause = "out of scope"
                self._gcs_dirty += 1
                return
            else:
                busy = False
        if busy:
            t = threading.Timer(self._ACTOR_GC_DEFER_S,
                                self._maybe_gc_actor, args=(actor_id,))
            t.daemon = True
            t.start()
            return
        self.kill_actor(actor_id, no_restart=True)

    def kill_actor(self, actor_id: bytes, no_restart=True):
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return
            if no_restart:
                actor.restarts_left = 0
                # Snapshot must observe the kill: a restarted head must
                # not resurrect an actor the user explicitly destroyed.
                self._gcs_dirty += 1
            worker = actor.worker
            if worker is not None:
                try:
                    worker.proc.terminate()
                except Exception:
                    pass

    def actor_exit(self, actor_id: bytes):
        """Graceful __ray_terminate__ equivalent."""
        self.kill_actor(actor_id, no_restart=True)

    def get_named_actor(self, name, namespace="default"):
        with self.lock:
            aid = self.named_actors.get((namespace, name))
            if aid is None:
                raise ValueError(f"No actor named {name!r}")
            return aid, self.actors[aid]

    # ---------------------------------------------------- placement groups --
    def create_placement_group(self, bundles, strategy="PACK", name=""):
        pg = PlacementGroupState(PlacementGroupID.from_random(), bundles,
                                 strategy, name)
        with self.lock:
            self.placement_groups[pg.pg_id.binary()] = pg
            self.pending_pgs.append(pg)
            self._try_reserve_pgs_locked()
        return pg

    def _pg_can_fit_locked(self, pg, idx: int, req: Dict[str, float]) -> bool:
        bundle = pg.bundles[idx]
        used = pg.used[idx]
        return all(bundle.get(k, 0.0) - used.get(k, 0.0) >= v - 1e-9
                   for k, v in req.items())

    def _pg_acquire_locked(self, pg, idx: int, req: Dict[str, float]):
        used = pg.used[idx]
        for k, v in req.items():
            used[k] = used.get(k, 0.0) + v

    def _pg_release_locked(self, pg, idx: int, req: Dict[str, float]):
        used = pg.used[idx]
        for k, v in req.items():
            used[k] = used.get(k, 0.0) - v

    def _try_reserve_pgs_locked(self):
        """2-phase bundle reservation condensed to one phase under the global
        lock (reference: GcsPlacementGroupScheduler prepare/commit)."""
        still = deque()
        while self.pending_pgs:
            pg = self.pending_pgs.popleft()
            if pg.removed:
                continue
            plan = self._plan_pg_locked(pg)
            if plan is None:
                still.append(pg)
                continue
            for idx, node in enumerate(plan):
                node.acquire(pg.bundles[idx])
                pg.reserved[idx] = node.node_id
            if not pg.created_future.done():
                pg.created_future.set_result(True)
        self.pending_pgs = still

    def _plan_pg_locked(self, pg) -> Optional[List[NodeState]]:
        alive = [self.nodes[nid] for nid in self.node_order
                 if self.nodes[nid].alive
                 and not self.nodes[nid].draining]
        avail = {id(n): dict(n.available) for n in alive}

        def fits(n, b):
            return all(avail[id(n)].get(k, 0) >= v - 1e-9
                       for k, v in b.items())

        def take(n, b):
            for k, v in b.items():
                avail[id(n)][k] = avail[id(n)].get(k, 0) - v

        plan: List[NodeState] = []
        if pg.strategy in ("PACK", "STRICT_PACK"):
            for n in alive:
                trial = []
                ok = True
                snapshot = {k: dict(v) for k, v in avail.items()}
                for b in pg.bundles:
                    if fits(n, b):
                        take(n, b)
                        trial.append(n)
                    else:
                        ok = False
                        break
                if ok:
                    return trial
                avail.update(snapshot)
            if pg.strategy == "STRICT_PACK":
                return None
        if pg.strategy in ("SPREAD", "STRICT_SPREAD", "PACK"):
            used_nodes = set()
            for b in pg.bundles:
                placed = None
                for n in alive:
                    if pg.strategy == "STRICT_SPREAD" and id(n) in used_nodes:
                        continue
                    if fits(n, b):
                        placed = n
                        break
                if placed is None:
                    return None
                take(placed, b)
                used_nodes.add(id(placed))
                plan.append(placed)
            return plan
        return None

    def remove_placement_group(self, pg_id: bytes):
        with self.lock:
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg.removed:
                return
            pg.removed = True
            for idx, node_id in enumerate(pg.reserved):
                if node_id is not None and node_id in self.nodes:
                    self.nodes[node_id].release(pg.bundles[idx])
            self._try_reserve_pgs_locked()
            self._dispatch_locked()

    # ----------------------------------------------------- per-conn readers --
    def _worker_reader(self, conn, worker: WorkerHandle):
        """One thread per worker connection (reference: each core worker's
        gRPC stream is served independently — the single select loop of v1
        serialized all control traffic through one thread)."""
        while not self._stopped:
            try:
                msg = protocol.recv(conn)
            except (EOFError, OSError, TypeError):
                # TypeError: conn.close()d out from under a blocked recv
                # (its handle becomes None mid-read).
                self._on_worker_death(worker)
                return
            try:
                self._handle_worker_msg(worker, msg)
            except Exception:
                import traceback
                traceback.print_exc()

    def _agent_reader(self, conn, agent: "AgentHandle"):
        while not self._stopped:
            try:
                msg = protocol.recv(conn)
            except (EOFError, OSError, TypeError):
                self._on_agent_death(agent)
                return
            # Failure detection: ANY agent message is liveness (the
            # heartbeat floor guarantees at least one per period).
            # Benign unlocked write — the suspicion loop reads it
            # monotonically.
            agent.last_seen = time.monotonic()
            try:
                self._handle_agent_msg(agent, msg)
            except Exception:
                import traceback
                traceback.print_exc()

    def _handle_agent_msg(self, agent: AgentHandle, msg: tuple):
        if msg[0] == "heartbeat":
            pass  # liveness stamped by the reader wrapper
        elif msg[0] == "segment":
            agent.deliver(msg[1], msg[2], msg[3])
        elif msg[0] == "oom_pressure":
            # The node's agent sampled its own memory over threshold;
            # the victim policy runs here where the task table lives.
            self._oom_kill_one(msg[1], node=agent.node)
        elif msg[0] == "worker_logs":
            node_hex = (agent.node.node_id.hex()
                        if agent.node is not None else "")
            for wid, lines in msg[1]:
                self._record_worker_lines(wid, lines, node=node_hex)
        elif msg[0] == "preempt_notice":
            # Spot/preemptible warning window: drain the node within the
            # agent's deadline, then release it with drain_node so the
            # agent exits before the plug pulls.  Off-thread — the drain
            # waits on checkpoints and migration pulls, and this is the
            # agent's reader thread.  With elastic_drain off the notice
            # is ignored (the agent also never sends one then: the head
            # withheld drain_caps in agent_ack) and the node death rides
            # the legacy hard-kill path with every counter zero.
            if self.config.elastic_drain and agent.node is not None:
                with self.lock:
                    self.preemptions += 1
                threading.Thread(
                    target=self.drain_node,
                    args=(agent.node.node_id, msg[1], msg[2]),
                    daemon=True, name="ray_tpu-drain").start()

    def _on_agent_death(self, agent: AgentHandle):
        """Node agent connection dropped: the node is gone (reference: GCS
        health-check failure -> node death broadcast,
        gcs_health_check_manager.h:39)."""
        with self.lock:
            if agent.dead:
                return
            agent.dead = True
            self._conn_to_agent.pop(agent.conn, None)
            self._agents.pop(agent.store_id, None)
            node = agent.node
            if node is not None:
                node.alive = False
            workers = list(node.all_workers.values()) if node else []
        agent.fail_all(exc.RayTpuError("node agent died"))
        # Its workers are unreachable (and die with the agent when it exits
        # cleanly).  Drive the death path directly — a closed conn makes
        # connection.wait() raise rather than report EOF, so waiting on the
        # IO loop to notice would spin.
        for w in workers:
            conn = w.conn
            self._on_worker_death(w)
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass

    def _handle_worker_msg(self, worker: WorkerHandle, msg: tuple):
        """Per-handler latency accounting wraps every control message
        (reference: src/ray/common/event_stats.h — per-handler event
        stats; this is the instrumentation that shows WHERE head time
        goes under load)."""
        if protocol.is_batch(msg):
            # Wire-batch envelope: unwrap so each sub-message keeps its
            # own handler stats AND its own failure isolation — a bad
            # sub-message must not abort the rest of the frame (they
            # were independent messages before batching).
            for m in msg[1]:
                try:
                    self._handle_worker_msg(worker, m)
                except Exception:
                    import traceback
                    traceback.print_exc()
            return
        # Failure detection: any worker message is liveness (benign
        # unlocked write; the suspicion loop reads it monotonically).
        worker.last_seen = time.monotonic()
        t0 = time.perf_counter()
        try:
            return self._handle_worker_msg_inner(worker, msg)
        finally:
            dt = time.perf_counter() - t0
            tag = msg[0] if isinstance(msg[0], str) else "?"
            with self._handler_stats_lock:
                s = self._handler_stats.get(tag)
                if s is None:
                    s = self._handler_stats[tag] = [0, 0.0, 0.0]
                s[0] += 1
                s[1] += dt
                if dt > s[2]:
                    s[2] = dt

    def _handle_worker_msg_inner(self, worker: WorkerHandle, msg: tuple):
        tag = msg[0]
        if tag == "ready":
            worker.ready.set()
        elif tag == "heartbeat":
            pass  # liveness stamped by the handler wrapper
        elif tag == "hc_ping":
            # Stalled-head watchdog probe from a worker/client stuck
            # waiting on us: any reply resets its clock.  Rides the
            # conflation sender — proving the whole send path moves is
            # the point.
            self._queue_send(worker, ("reply", msg[1], "pong"))
        elif tag == "spans":
            # Task execution spans from a worker (task events; feeds
            # `ray_tpu.timeline()` — scripts.py:1840 `ray timeline`).
            wid = worker.worker_id.hex()
            nid = (worker.node.node_id.hex()
                   if worker.node is not None else "")
            with self.lock:
                for tid_bin, name, start, end, kind in msg[1]:
                    self.task_spans.append({
                        "task_id": tid_bin.hex(), "name": name,
                        "start": start, "end": end, "kind": kind,
                        "worker_id": wid, "node_id": nid})
        elif tag == "event":
            # Generic worker->driver pubsub (reference: src/ray/pubsub/
            # long-poll channels) — used by train session streaming and
            # the serve controller's autoscale events.
            with self.lock:
                self.events.setdefault(msg[1], deque(maxlen=10000)).append(
                    msg[2])
                listeners = list(self._event_listeners.get(msg[1], ()))
            for cb in listeners:
                # Outside the lock: a listener (the autoscaler's wake)
                # may take its own locks; it must only nudge, not block.
                try:
                    cb()
                except Exception:
                    pass
        elif tag == "xfer_stats":
            # Periodic data-plane counter DELTAS from a worker (pull
            # dedup, argument-prefetch hit/waste bytes) — aggregated
            # here next to brokered_parts/relayed_segments.
            with self.lock:
                d = msg[1]
                self.deduped_pulls += d.get("deduped_pulls", 0)
                self.prefetch_hit_bytes += d.get("prefetch_hit_bytes", 0)
                self.prefetch_waste_bytes += d.get(
                    "prefetch_waste_bytes", 0)
                self.leased_submits += d.get("leased_submits", 0)
                self.spillbacks += d.get("spillbacks", 0)
                # Worker-owned (direct-path) lineage reconstructions ride
                # the same delta stream as every holder-side counter.
                self.reconstructions += d.get("reconstructions", 0)
                self.reconstruction_failures += d.get(
                    "reconstruction_failures", 0)
                # Failure-detection deltas from the worker's deadline
                # core (zero with the switch off).
                self.stall_timeouts += d.get("stall_timeouts", 0)
                self.net_retries += d.get("net_retries", 0)
                self.hedged_fetches += d.get("hedged_fetches", 0)
                # Push-shuffle deltas from map tasks and reducer
                # actors (zero with the switch off).
                self.shuffle_pushed_bytes += d.get(
                    "shuffle_pushed_bytes", 0)
                self.shuffle_merges += d.get("shuffle_merges", 0)
                self.shuffle_spills += d.get("shuffle_spills", 0)
                self.shuffle_hedges += d.get("shuffle_hedges", 0)
                # Distributed-training deltas from pipeline stage
                # actors / IMPALA learner workers (zero with the
                # switch off).
                self.microbatch_pushes += d.get("microbatch_pushes", 0)
                self.stage_restarts += d.get("stage_restarts", 0)
                self.learner_queue_stalls += d.get(
                    "learner_queue_stalls", 0)
        elif tag == "result":
            self._on_result(worker, msg[1], msg[2], msg[3], msg[4])
        elif tag == "result_batch":
            for tid_bin, ok, returns, meta in msg[1]:
                self._on_result(worker, tid_bin, ok, returns, meta)
        elif tag == "getparts":
            # Worker holds a descriptor for a segment in another node's
            # store: ship the serialized parts.  Fetch may block on a
            # remote agent, so it runs off the IO thread.
            rid, descr = msg[1], msg[2]
            with self.lock:
                self.brokered_parts += 1

            def fetch_and_reply(worker=worker, rid=rid, descr=descr):
                try:
                    # The worker's descriptor may be stale (object spilled
                    # or restored since): the owner's table has the current
                    # location.
                    cur_oid = self._oid_from_segment_name(descr[1])
                    if cur_oid is not None:
                        with self.lock:
                            st0 = self.objects.get(cur_oid)
                            if st0 is not None and st0.descr is not None \
                                    and st0.descr[0] in (protocol.SHM,
                                                         protocol.SPILLED):
                                descr = st0.descr
                    try:
                        meta, bufs = self._fetch_parts(descr)
                    except exc.ObjectLostError:
                        # Home store died: recover by lineage re-execution,
                        # then ship the rebuilt object (reference:
                        # object_recovery_manager.h:41).
                        oid = self._oid_from_segment_name(descr[1])
                        if oid is None \
                                or not self._recover_for_worker(worker,
                                                                oid):
                            raise
                        with self.lock:
                            st = self.objects.get(oid)
                            descr2 = st.descr if st is not None else None
                        if descr2 is None:
                            raise
                        if descr2[0] != protocol.SHM:
                            worker.send(("obj", rid, True, descr2))
                            return
                        meta, bufs = self._fetch_parts(descr2)
                    # Direct pulls hand back memoryviews (zero-copy for
                    # driver-local use); pickling the reply needs bytes.
                    bufs = [b if isinstance(b, bytes) else bytes(b)
                            for b in bufs]
                    # Direct send, NOT the conflation sender: this reply
                    # can carry hundreds of MB of PARTS bytes, and this
                    # fetch thread is already per-request — funneling it
                    # through the one sender thread would head-of-line
                    # block exec dispatch to every other worker.
                    worker.send(("obj", rid, True,
                                 (protocol.PARTS, meta, bufs)))
                except BaseException as e:  # noqa: BLE001
                    err = serialization.dumps_inline(
                        e if isinstance(e, exc.RayTpuError)
                        else exc.ObjectLostError(
                            repr(e), object_id=_seg_oid_hex(descr[1]),
                            phase="relay"))
                    worker.send(("obj", rid, False, (protocol.ERROR, err)))

            threading.Thread(target=fetch_and_reply, daemon=True).start()
        elif tag == "wait":
            _, rid, id_bins, num_returns, timeout = msg
            from ray_tpu._private.object_ref import ObjectRef

            def respond():
                with self.lock:
                    ready_ids = [
                        b for b in id_bins
                        if (st := self.objects.get(ObjectID(b))) is not None
                        and st.status != PENDING
                    ]
                self._queue_send(worker,
                                 ("waited", rid, ready_ids[:num_returns]))

            count = {"ready": 0, "sent": False}
            with self.lock:
                pend = []
                for b in id_bins:
                    st = self.objects.get(ObjectID(b))
                    if st is None or st.status != PENDING:
                        count["ready"] += 1
                    else:
                        pend.append(st)
                if count["ready"] >= num_returns or not pend \
                        or timeout == 0:
                    # timeout == 0 is a PROBE (the mixed-ownership wait
                    # poll): answer immediately, register nothing — no
                    # leaked waiter callbacks or Timer threads per poll.
                    count["sent"] = True
                else:
                    # The wait really blocks this worker: steal back its
                    # pipelined-but-unstarted tasks — one of them may be
                    # what the wait awaits (same head-of-line hazard as
                    # the mget path).
                    stealable = [tid for tid, r in worker.inflight.items()
                                 if not r.is_actor_creation]
                    if stealable:
                        try:
                            self._queue_send(worker, ("steal", 0, stealable))
                        except Exception:
                            pass
                    def cb(_oid):
                        count["ready"] += 1
                        if count["ready"] >= num_returns and not count["sent"]:
                            count["sent"] = True
                            threading.Thread(target=respond,
                                             daemon=True).start()
                    for st in pend:
                        st.waiters.append(cb)
                    if timeout is not None:
                        threading.Timer(timeout, lambda: (
                            None if count["sent"]
                            else (count.__setitem__("sent", True), respond())
                        )).start()
            if count["sent"]:
                respond()
        elif tag == "submit":
            # Fire-and-forget (reference: PushNormalTask pipelining,
            # direct_task_transport.cc:568): the worker built its return
            # refs locally; per-connection FIFO guarantees any later use
            # of them arrives after this spec.
            self.submit_task_from_worker(msg[2], submitter=worker)
            self._maybe_offer_lease(worker, [msg[2]])
        elif tag == "submit_batch":
            # Bulk fire-and-forget submission (worker/client fan-out):
            # one lock pass + one dispatch for the whole list.  A burst
            # of direct-eligible specs arriving HERE means the holder is
            # lease-starved: piggyback a bulk lease grant on the exchange
            # so the next burst rides the direct plane.
            self.submit_tasks_from_worker(msg[1], submitter=worker)
            self._maybe_offer_lease(worker, msg[1])
        elif tag == "create_actor_req":
            _, rid, spec, creation_opts = msg
            try:
                actor_id = self.create_actor(spec, creation_opts)
                self._queue_send(worker, ("reply", rid, actor_id))
            except Exception as e:  # noqa: BLE001
                self._queue_send(worker, ("reply", rid, e))
        elif tag == "store_addr":
            # Location brokering only (reference: the owner-based object
            # directory answering WHERE, never carrying bytes).  Replies
            # (addr, caps): the advertised verb set lets pullers stripe
            # against peers that speak fetch_range without ever probing
            # one that doesn't.  The HEAD's own store has an object
            # server too, so head-homed segments are pulled directly
            # instead of relayed through getparts.  Compat note: a
            # pre-caps worker handed this tuple fails its address parse
            # and degrades to the (pre-existing) getparts relay — safe
            # but slow; the inverse (new worker, bare-addr old head) is
            # parsed explicitly in _direct_pull.  A new request tag
            # can't fix this: old heads drop unknown tags without
            # replying, which would hang the requester instead.
            _, rid, store_hex = msg
            if store_hex == self.store_id:
                reply = (self.object_addr,
                         self._adv_caps(object_transfer.CAPS))
            else:
                with self.lock:
                    agent = self._agents.get(store_hex)
                    alive = agent is not None and not agent.dead
                    addr = (agent.info.get("object_addr")
                            if alive else None)
                    caps = (self._adv_caps(agent.info.get("object_caps"))
                            if alive else ())
                reply = (addr, caps) if addr else None
            self._queue_send(worker, ("reply", rid, reply))
        elif tag == "state_req":
            _, rid, kind, kwargs = msg
            try:
                self._queue_send(
                    worker, ("reply", rid, self.state_query(kind, **kwargs)))
            except Exception as e:  # noqa: BLE001
                self._queue_send(worker, ("reply", rid, e))
        elif tag == "kill_actor_req":
            _, rid, actor_id, no_restart = msg
            self.kill_actor(actor_id, no_restart)
            self._queue_send(worker, ("reply", rid, True))
        elif tag == "get_actor_req":
            _, rid, name, namespace = msg
            try:
                actor_id, actor = self.get_named_actor(name, namespace)
                self._queue_send(
                    worker, ("reply", rid,
                             (True, actor_id,
                              actor.options.get("method_names", {}))))
            except ValueError:
                self._queue_send(worker, ("reply", rid, (False, None, None)))
        elif tag == "put_parts":
            # Legacy client-shipped value: land it in the HEAD's store
            # so any worker can consume it (clients share no /dev/shm).
            # The table entry registers PENDING under the lock here (so
            # later messages on this FIFO see the object), but the
            # multi-hundred-MB assembly memcpy runs OFF this reader
            # thread and outside the runtime lock — the PR 6 lock-hold
            # convention: the lock is held only for table registration.
            _, oid_bin, meta, bufs, nested = msg
            oid = ObjectID(oid_bin)
            with self.lock:
                if self.config.direct_puts:
                    # Counted only while the direct path is on: this
                    # message is then a FALLBACK (old-verb client, push
                    # failure) worth watching.
                    self.brokered_put_parts += 1
                st = self.objects.get(oid)
                if st is None:
                    st = self.objects[oid] = ObjectState()
                st.pins += 1  # assembly pin: no free mid-assembly
                st.nested_ids = list(nested)
                self._pin_nested_locked(st.nested_ids)

            def assemble(oid=oid, meta=meta, bufs=bufs):
                try:
                    descr = self._store_parts_locally(oid, meta, bufs)
                except Exception as e:  # noqa: BLE001
                    descr = (protocol.ERROR, serialization.dumps_inline(
                        exc.RayTpuError(f"client put failed: {e!r}")))
                finally:
                    self._put_assembly_sem.release()
                with self.lock:
                    st2 = self.objects.get(oid)
                    if st2 is not None:
                        st2.pins -= 1
                        self._register_put_locked(
                            oid, st2, descr, descr[0] != protocol.ERROR)
                        drop_candidate = st2.refcount() <= 0
                if st2 is not None and drop_candidate:
                    # Refs dropped DURING assembly: the decref's free ran
                    # into the assembly pin and deferred, and
                    # _register_put_locked deliberately skips the free
                    # check (the client's addref may still be in flight
                    # on its FIFO conn, microseconds behind).  Re-check
                    # after a beat — by then the addref has long landed
                    # if it is ever coming — so a fire-and-forget client
                    # put cannot leak its segment.
                    def _late_free(oid=oid):
                        with self.lock:
                            st3 = self.objects.get(oid)
                            if st3 is not None:
                                self._maybe_free_locked(oid, st3)

                    threading.Timer(1.0, _late_free).start()
                if st2 is None:
                    # Entry freed mid-assembly (ref dropped): don't leak
                    # the just-written segment/spill file.
                    if descr[0] == protocol.SHM:
                        self.shm.unlink(descr[1], descr[2])
                    elif descr[0] == protocol.SPILLED:
                        try:
                            os.unlink(descr[1])
                        except OSError:
                            pass

            # Blocks this reader past the in-flight bound — deliberate:
            # the bursting client's TCP window then backpressures it,
            # as the old inline assembly did per connection.
            self._put_assembly_sem.acquire()  # noqa: RTL401 -- cross-thread handoff: released in assemble()'s finally on the assembly thread
            threading.Thread(target=assemble, daemon=True,
                             name="ray_tpu-put-parts").start()
        elif tag == "put_commit":
            # Direct-put commit: the payload already streamed into this
            # node's store over the data plane (object-server verbs
            # reserve_put/put_range/commit_put) — the control plane sees
            # only this O(1) descriptor registration, the write-direction
            # analog of the head staying out of the pull payload path.
            _, oid_bin, descr, nested = msg
            oid = ObjectID(oid_bin)
            with self.lock:
                self.direct_puts += 1
                if descr is not None and len(descr) > 2 \
                        and isinstance(descr[2], int):
                    self.direct_put_bytes += descr[2]
                st = self.objects.get(oid)
                if st is None:
                    st = self.objects[oid] = ObjectState()
                st.nested_ids = list(nested)
                self._pin_nested_locked(st.nested_ids)
                self._register_put_locked(oid, st, descr, True)
        elif tag in ("job_submit", "job_status", "job_logs", "job_stop",
                     "job_list"):
            from ray_tpu.job_submission import _get_manager

            mgr = _get_manager(self)
            try:
                if tag == "job_submit":
                    out = mgr.submit(msg[2], msg[3], msg[4])
                elif tag == "job_status":
                    out = mgr.status(msg[2])
                elif tag == "job_logs":
                    out = mgr.logs(msg[2])
                elif tag == "job_stop":
                    out = mgr.stop(msg[2])
                else:
                    out = mgr.list()
            except Exception as e:  # noqa: BLE001
                out = e
            self._queue_send(worker, ("reply", msg[1], out))
        elif tag == "get_package":
            blob = getattr(self, "_pkg_cache", {}).get(msg[2])
            self._queue_send(worker, ("reply", msg[1], blob))
        elif tag == "cluster_info":
            self._queue_send(worker, ("reply", msg[1], {
                "resources": self.cluster_resources(),
                "available": self.available_resources(),
                "nodes": self.list_nodes(),
                "session_id": self.session_id,
            }))
        elif tag == "put":
            _, oid_bin, descr, nested = msg
            oid = ObjectID(oid_bin)
            with self.lock:
                st = self.objects.get(oid)
                if st is None:
                    st = self.objects[oid] = ObjectState()
                st.status = READY
                st.descr = descr
                if descr[0] == protocol.SHM:
                    st.creator = worker
                st.nested_ids = list(nested)
                self._pin_nested_locked(st.nested_ids)
        elif tag == "addref":
            with self.lock:
                oid = ObjectID(msg[1])
                st = self.objects.get(oid)
                if st is None:
                    st = self.objects[oid] = ObjectState()
                st.worker_refs += 1
        elif tag == "decref":
            with self.lock:
                oid = ObjectID(msg[1])
                st = self.objects.get(oid)
                if st is not None:
                    st.worker_refs -= 1
                    self._maybe_free_locked(oid, st)
        elif tag == "decref_batch":
            with self.lock:
                for b in msg[1]:
                    oid = ObjectID(b)
                    st = self.objects.get(oid)
                    if st is not None:
                        st.worker_refs -= 1
                        self._maybe_free_locked(oid, st)
        elif tag == "actor_addref":
            self.actor_handle_addref(msg[1])
        elif tag == "actor_decref_batch":
            for aid in msg[1]:
                self.actor_handle_decref(aid)
        elif tag == "actor_token_new":
            self.actor_handle_serialized(msg[1], msg[2])
        elif tag == "actor_token_used":
            self.actor_handle_deserialized(msg[1], msg[2])
        elif tag == "addref_batch":
            with self.lock:
                for b in msg[1]:
                    oid = ObjectID(b)
                    st = self.objects.get(oid)
                    if st is None:
                        st = self.objects[oid] = ObjectState()
                    st.worker_refs += 1
        elif tag == "actor_addr_req":
            # Resolve an actor to its worker's direct endpoint so the
            # caller can push method calls straight to it (reference:
            # direct_actor_task_submitter resolving the actor's address
            # via the GCS actor table).
            _, rid, aid = msg
            with self.lock:
                actor = self.actors.get(aid)
            if actor is None:
                worker_send_safe(worker, ("reply", rid, None))
            else:
                def on_created(_fut, aid=aid, rid=rid, lessee=worker):
                    with self.lock:
                        a = self.actors.get(aid)
                        w = (a.worker if a is not None and a.status == ALIVE
                             else None)
                        out = ((w.worker_id.hex(), tuple(w.direct_addr))
                               if w is not None and not w.dead
                               and w.direct_addr else None)
                    worker_send_safe(lessee, ("reply", rid, out))

                actor.created_future.add_done_callback(on_created)
        elif tag == "lease_req":
            # A caller wants executor workers to push tasks to directly;
            # the head only does the resource accounting (reference: the
            # raylet's RequestWorkerLease, direct_task_transport.cc:568).
            opts = msg[4] if len(msg) > 4 else None
            if opts and opts.get("v"):
                # The peer just proved it speaks the v1 lease plane:
                # unsolicited grants may now be pushed to it too.
                worker.lease_caps = True
            self._grant_client_leases(worker, msg[1], msg[2], msg[3],
                                      opts)
        elif tag == "lease_renew":
            # Holder liveness, one message per N leased pushes: bump the
            # named leases' TTL deadlines (pushed tasks never touch the
            # head, so this is the only signal the holder is still
            # driving them).
            if self.config.lease_ttl_s > 0:
                expiry = time.monotonic() + self.config.lease_ttl_s
                with self.lock:
                    for wid in msg[1]:
                        w = self._workers_by_hex.get(wid)
                        if w is not None and w.client_lease is worker \
                                and not w.dead:
                            w.lease_expiry = expiry
        elif tag == "lease_return":
            with self.lock:
                for wid in msg[1]:
                    w = self._workers_by_hex.get(wid)
                    if w is not None and w.client_lease is not None \
                            and not w.dead:
                        w.client_lease = None
                        self._end_lease_locked(w)
                self._request_dispatch_locked()
        elif tag == "export_obj":
            # A worker delegates ownership of objects it created to the
            # head (they are about to be consumed through head-routed
            # specs or returned values).  worker_refs starts at 1: one
            # aggregate ref standing for all of the exporter's local refs.
            with self.lock:
                for item in msg[1]:
                    b, ok, descr, nested = item[0], item[1], item[2], item[3]
                    creator_hex = item[4] if len(item) > 4 else None
                    oid = ObjectID(b)
                    st = self.objects.get(oid)
                    if st is None:
                        st = self.objects[oid] = ObjectState()
                    st.worker_refs += 1
                    if ok is None:
                        # Pending shell; export_complete follows — unless
                        # the exporter dies first (death path fails it).
                        st.exporter = worker
                        continue
                    st.nested_ids = list(nested)
                    self._pin_nested_locked(st.nested_ids)
                    if descr is not None and descr[0] == protocol.SHM:
                        st.shipped = True
                    cw = (self._workers_by_hex.get(creator_hex)
                          if creator_hex else worker)
                    # _complete_object_locked (not a bare status write):
                    # a consumer may ALREADY be blocked on this object —
                    # e.g. it deserialized the ref from a direct task's
                    # container arg before this export was processed —
                    # and its mget waiter must fire.
                    self._complete_object_locked(
                        oid, descr, bool(ok),
                        creator=(cw if cw is not None and not cw.dead
                                 else None))
        elif tag == "export_complete":
            with self.lock:
                for item in msg[1]:
                    b, ok, descr = item[0], item[1], item[2]
                    nested = item[3] if len(item) > 3 else []
                    creator_hex = item[4] if len(item) > 4 else None
                    oid = ObjectID(b)
                    st = self.objects.get(oid)
                    if st is not None and nested:
                        st.nested_ids = list(nested)
                        self._pin_nested_locked(st.nested_ids)
                    if st is not None and descr is not None \
                            and descr[0] == protocol.SHM:
                        st.shipped = True
                    cw = (self._workers_by_hex.get(creator_hex)
                          if creator_hex else None)
                    if st is not None:
                        st.exporter = None
                    self._complete_object_locked(oid, descr, bool(ok),
                                                 creator=cw)
        elif tag == "descr_update":
            # Owner spilled a delegated object: its head descriptor
            # flips to the spill location (consumers restore through
            # the normal SPILLED paths).
            with self.lock:
                for b, descr in msg[1]:
                    st = self.objects.get(ObjectID(b))
                    if st is not None and st.status != PENDING:
                        st.descr = descr
        elif tag == "free_remote":
            # Owner-side free of a segment homed in another store (its
            # direct conn to the creator is gone): route the unlink.
            _, name, size, store_hex = msg
            if store_hex == self.store_id:
                try:
                    self.shm.unlink(name, size, reusable=False)
                except Exception:
                    pass
            else:
                with self.lock:
                    agent = self._agents.get(store_hex)
                if agent is not None and not agent.dead:
                    try:
                        agent.send(("unlink_segment", name, size))
                    except Exception:
                        pass
        elif tag == "mget":
            self._on_worker_mget(worker, msg[1], msg[2], msg[3])
        elif tag == "blocked":
            # A worker blocked in ray.get releases its lease's CPU slot so
            # the cluster can make progress (reference: raylet releases
            # resources for blocked workers, node_manager.cc).  PG tasks
            # keep their bundle slot — the gang reservation is the point.
            with self.lock:
                worker.blocked = True
                if (worker.lease_req is not None and not worker.released
                        and worker.lease_pg is None):
                    worker.node.release(worker.lease_req)
                    worker.released = True
                self._request_dispatch_locked()
        elif tag == "unblocked":
            with self.lock:
                worker.blocked = False
                if worker.lease_req is not None and worker.released:
                    worker.node.acquire(worker.lease_req)
                    worker.released = False
                self._request_dispatch_locked()
        elif tag == "stolen":
            # Tasks the worker relinquished (never started): re-dispatch
            # elsewhere.  Their results can no longer arrive from it.
            with self.lock:
                for tid_bin in msg[2]:
                    rec = worker.inflight.pop(tid_bin, None)
                    if rec is None:
                        continue
                    if rec.cancelled:
                        self._fail_task_locked(rec, exc.TaskCancelledError(
                            rec.spec.get("name", "task")))
                        continue
                    rec.dispatched = False
                    rec.worker = None
                    self._enqueue_pending_locked(rec)
                if worker.pending_force_kill is not None:
                    victim = worker.pending_force_kill
                    worker.pending_force_kill = None
                    if victim in worker.inflight:
                        # Victim already started: kill the process (the
                        # bystanders were just stolen back).
                        try:
                            worker.proc.terminate()
                        except Exception:
                            pass
                if not worker.inflight and worker.lease_req is not None \
                        and not worker.dead and worker.actor_id is None:
                    self._end_lease_locked(worker)
                self._request_dispatch_locked()
        elif tag == "reregister":
            # In-band re-registration from a CLIENT that re-dialed after
            # a head restart (its conn-level handshake already ran via
            # client_ready): reconcile its claims — held leases and
            # re-advertised owned objects.  Gated like the worker path:
            # with the failover switch off nothing reconciles and every
            # failover counter stays zero (the client session itself
            # still works — it re-entered through client_ready).
            if self.config.head_failover:
                with self.lock:
                    self.reregistered_workers += 1
                    self._apply_reregister_claims_locked(worker, msg[1])
        elif tag == "resubmit_batch":
            # Failover replay: specs whose fate at the dead head is
            # unknown to the submitter.  At-least-once semantics (the
            # reference's retry contract): skip anything already known
            # or already completed, run the rest.
            with self.lock:
                fresh = []
                for spec in msg[1]:
                    tid_bin = spec["task_id"]
                    if tid_bin in self.tasks:
                        continue
                    tid = TaskID(tid_bin)
                    sts = [self.objects.get(tid.object_id(i))
                           for i in range(max(1, spec["num_returns"]))]
                    if all(s is not None and s.status != PENDING
                           for s in sts):
                        continue
                    fresh.append(spec)
            if fresh:
                self.submit_tasks_from_worker(fresh, submitter=worker)
        elif tag == "actor_checkpoint":
            # Latest __ray_save__ state from a restartable actor's
            # worker: retain the descriptor for the next restart's
            # __ray_restore__; the superseded checkpoint's storage is
            # freed (checkpoints live outside the object table).
            aid, descr = msg[1], msg[2]
            forced = len(msg) > 3 and bool(msg[3])
            if descr is not None and descr[0] == protocol.PARTS:
                # Drain-forced checkpoint: the worker shipped raw parts
                # because its own store is about to die with the node —
                # re-home the state on the HEAD's surviving store before
                # retaining the descriptor (outside the lock: a big
                # create_from_parts must not stall the reader).
                try:
                    descr = self._store_parts_locally(
                        ObjectID.for_put(), descr[1], descr[2])
                except Exception:
                    descr = None
            ck_ev = None
            with self.lock:
                # A drain waiting on this actor's FORCED checkpoint is
                # released even when the reply carries no state (hookless
                # actor / failed save): the drain must not stall a full
                # deadline on an actor that can never checkpoint.  Only
                # the forced reply releases it — a racing periodic
                # checkpoint (node-homed, mid-flight at drain start)
                # must not end the wait before the re-homed state lands.
                if forced:
                    ck_ev = self._drain_ck_events.pop(aid, None)
                if descr is not None:
                    actor = self.actors.get(aid)
                    if actor is None or actor.status == DEAD:
                        # Racing a death/GC: don't strand the bytes.
                        self._free_checkpoint_locked(actor, descr)
                    elif self._ck_home_dying_locked(descr) \
                            and actor.checkpoint is not None \
                            and not self._ck_home_dying_locked(
                                actor.checkpoint):
                        # A periodic checkpoint homed on a DRAINING/dead
                        # store must never supersede a safely-homed one:
                        # the exec thread's post-method save races the
                        # drain's forced re-homed checkpoint, and losing
                        # that race would strand the restart on a store
                        # that dies with the node.
                        self._free_checkpoint_locked(None, descr)
                    else:
                        old, actor.checkpoint = actor.checkpoint, descr
                        if old is not None:
                            self._free_checkpoint_locked(actor, old)
            if ck_ev is not None:
                ck_ev.set()

    def submit_task_from_worker(self, spec: dict, submitter=None):
        """Nested submission: worker-generated task, driver-owned objects."""
        self.submit_tasks_from_worker([spec], submitter=submitter)

    def submit_tasks_from_worker(self, specs: List[dict], submitter=None):
        """Bulk form of the nested-submission path (the wire carries it
        as one ("submit_batch", [spec, ...]) message): every spec
        registers under ONE lock acquisition, then one dispatch pass /
        one pump per distinct actor covers the whole batch."""
        self._submit_specs(specs, from_worker=True, submitter=submitter)

    def _on_worker_mget(self, worker: WorkerHandle, rid, id_bins, timeout):
        """Batched worker get: ONE reply listing (ok, descr) per id, sent
        when all are complete (or the timeout fires).  Reference:
        CoreWorker::Get resolves the whole batch (core_worker.cc:1250)."""
        state = {"left": 0, "done": False, "timer": None}

        def finish_locked():
            if state["done"]:
                return
            state["done"] = True
            if state["timer"] is not None:
                state["timer"].cancel()
            out = []
            for b in id_bins:
                st = self.objects.get(ObjectID(b))
                if st is None:
                    err = serialization.dumps_inline(exc.ObjectFreedError(  # noqa: RTL604 -- bounded-small error payload on the miss path
                        object_id=b.hex(), owner="driver", phase="get"))
                    out.append((False, (protocol.ERROR, err)))
                elif st.status == PENDING:
                    err = serialization.dumps_inline(exc.GetTimeoutError(  # noqa: RTL604 -- bounded-small error payload on the timeout path
                        f"Timed out getting {b.hex()} after {timeout}s"))
                    out.append((False, (protocol.ERROR, err)))
                else:
                    st.shipped = True
                    out.append((st.status == READY, st.descr))
            try:
                self._queue_send(worker, ("mgot", rid, out))
            except Exception:
                # Requester died mid-wait: never let its broken conn abort
                # the completing worker's result handling (this runs inside
                # _complete_object_locked's waiter loop).
                pass

        with self.lock:
            if time.monotonic() < self._failover_grace_until:
                # Post-restart grace: an unknown id may belong to the
                # blip window (task finished after the last snapshot, or
                # still running on a worker that has not re-registered
                # yet).  Park it as implicitly-PENDING instead of
                # insta-failing; the reconcile timer fails the remainder
                # as reconstruction candidates.
                for b in id_bins:
                    oid = ObjectID(b)
                    if oid not in self.objects:
                        self.objects[oid] = ObjectState()
                        self._grace_objects.add(b)
            pend = [st for b in id_bins
                    if (st := self.objects.get(ObjectID(b))) is not None
                    and st.status == PENDING]
            if not pend:
                # Everything ready: answer immediately, no steal — the
                # worker unblocks right away, so stripping its pipeline
                # would be pure churn.
                finish_locked()
                return
            # The get really waits.  Steal back the worker's pipelined-but-
            # unstarted tasks: one of them may be (or produce a dependency
            # of) exactly what this get awaits — the head-of-line deadlock
            # (reference: work stealing in direct_task_transport).  The
            # worker replies "stolen" with the ids it had not started.
            stealable = [tid for tid, r in worker.inflight.items()
                         if not r.is_actor_creation]
            if stealable:
                try:
                    self._queue_send(worker, ("steal", 0, stealable))
                except Exception:
                    pass
            state["left"] = len(pend)

            def cb(_oid):  # runs under self.lock (RLock) in _complete
                state["left"] -= 1
                if state["left"] == 0:
                    finish_locked()

            for st in pend:
                st.waiters.append(cb)
            if timeout is not None:
                def on_timeout():
                    with self.lock:
                        finish_locked()
                t = state["timer"] = threading.Timer(timeout, on_timeout)
                t.daemon = True
                t.start()

    def _on_result(self, worker: WorkerHandle, task_id_bin, ok, returns,
                   meta):
        recovery.syncpoint("result")
        retry_err = None
        if not ok and returns and returns[0][0] == protocol.ERROR:
            # Only tasks that OPTED INTO retry_exceptions get their
            # error payload deserialized (outside the lock — RTL402);
            # for everyone else the head keeps treating error bytes as
            # opaque, exactly as before — a failure storm must not turn
            # the result loop into a user-exception unpickling loop.
            with self.lock:
                rec0 = self.tasks.get(task_id_bin)
                wants_retry = (rec0 is not None
                               and rec0.spec.get("retry_exceptions")
                               and rec0.app_retries_left > 0
                               and rec0.actor_id is None
                               and not rec0.is_actor_creation
                               and not rec0.cancelled)
            if wants_retry:
                # An unloadable payload just skips the retry check.
                try:
                    retry_err = serialization.loads_inline(returns[0][1])
                except Exception:
                    retry_err = None
        with self.lock:
            rec = self.tasks.pop(task_id_bin, None)
            if rec is None:
                # No record, but PENDING return entries exist: a blip-
                # window result (task finished while the head was down;
                # the worker's outbox replayed it after re-register).
                # Live retries keep their task record, so this can never
                # swallow a result a retry now owns.
                tid = TaskID(task_id_bin)
                for i, descr in enumerate(returns):
                    st = self.objects.get(tid.object_id(i))
                    if st is not None and st.status == PENDING:
                        self._complete_object_locked(
                            tid.object_id(i), descr,
                            descr[0] != protocol.ERROR, creator=worker)
                return
            if (retry_err is not None and not rec.is_actor_creation
                    and rec.actor_id is None and not rec.cancelled
                    and rec.app_retries_left > 0
                    and recovery.retry_matches(
                        rec.spec.get("retry_exceptions"), retry_err)):
                # Opt-in APPLICATION-error retry: re-queue the task
                # instead of completing its error objects.  Draws from
                # its own budget — the system-failure retries_left is
                # untouched (max_retries decrements only on worker/node
                # death; pinned by the retry-counting test).
                rec.app_retries_left -= 1
                rec.dispatched = False
                rec.worker = None
                self.tasks[task_id_bin] = rec
                worker.inflight.pop(task_id_bin, None)
                self.task_events.append(
                    {"task_id": task_id_bin.hex(),
                     "name": rec.spec.get("name"),
                     "state": "RETRYING", "time": time.time()})
                self._enqueue_pending_locked(rec)
                self._request_dispatch_locked([rec.sched_key])
                if not worker.inflight and not worker.dead \
                        and worker.lease_req is not None:
                    self._end_lease_locked(worker)
                    self._request_dispatch_locked()
                return
            tid = TaskID(task_id_bin)
            for i, descr in enumerate(returns):
                item_ok = descr[0] != protocol.ERROR
                self._complete_object_locked(tid.object_id(i), descr,
                                             item_ok, creator=worker)
            self._unpin_task_deps_locked(rec)
            self.task_events.append(
                {"task_id": task_id_bin.hex(),
                 "name": rec.spec.get("name"),
                 "state": "FINISHED" if ok else "FAILED",
                 "time": time.time()})
            if rec.is_actor_creation:
                actor = self.actors[rec.actor_id]
                worker.inflight.pop(task_id_bin, None)
                if actor.status == DEAD or rec.cancelled:
                    # GC'd (all handles dropped) or cancelled while the
                    # creation was in flight: the worker must not become
                    # a live actor nobody can ever reference — retire it
                    # and return its slot.
                    self._end_lease_locked(worker, reap=True)
                    self._dispatch_locked()
                    return
                if ok:
                    actor.status = ALIVE
                    actor.worker = worker
                    actor.node = rec.node
                    worker.actor_id = rec.actor_id
                    if not actor.created_future.done():
                        actor.created_future.set_result(True)
                    self._pump_actor_locked(actor)
                # failure path handled via _fail_task? create failure comes
                # back as result with ok=False:
                else:
                    err = serialization.loads_inline(returns[0][1])  # noqa: RTL402 -- cold actor-creation-failure path; inline error payloads are small
                    actor.status = DEAD
                    actor.death_cause = err
                    if not actor.created_future.done():
                        actor.created_future.set_exception(err)
                    self._fail_actor_queue_locked(actor, err)
                    self._end_lease_locked(worker, reap=True)
                return
            if worker.actor_id is not None:
                actor = self.actors.get(worker.actor_id)
                if actor is not None:
                    actor.inflight.pop(task_id_bin, None)
                    self._pump_actor_locked(actor)
                return
            worker.inflight.pop(task_id_bin, None)
            # Top up this worker's pipeline before deciding the lease is
            # over.  Sharded: only this worker's own class can have
            # gained a slot — scan just that shard inline; the global
            # pass runs (deferred) only when the lease actually ends and
            # returns resources anything could use.
            if self.config.decentralized_dispatch:
                if worker.lease_key is not None:
                    self._dispatch_class_locked(worker.lease_key)
                if not worker.inflight and not worker.dead \
                        and worker.lease_req is not None:
                    self._end_lease_locked(worker)
                    self._request_dispatch_locked()
            else:
                self._dispatch_locked()
                if not worker.inflight and not worker.dead \
                        and worker.lease_req is not None:
                    self._end_lease_locked(worker)

    def _reroute_dead_worker_frees_locked(self, worker: WorkerHandle):
        """A dead worker's buffered free_segment messages would vanish
        with its conn: run the store-side fallback unlink instead (the
        path the pre-conflation direct-send error handling took) so the
        segments don't leak until session end."""
        with worker.send_lock:
            msgs = worker.outbuf + worker.outbox
            worker.outbuf = []
            worker.outbox = []
        flat: List[tuple] = []
        for m in msgs:
            if protocol.is_batch(m):
                flat.extend(m[1])
            else:
                flat.append(m)
        agent = worker.node.agent if worker.node is not None else None
        for m in flat:
            if m[0] != "free_segment":
                continue
            name, size = m[1], m[2]
            if agent is None:
                try:
                    self.shm.unlink(name, size, reusable=False)
                except Exception:
                    pass
            elif not agent.dead:
                try:
                    agent.send(("unlink_segment", name, size))  # noqa: RTL604 -- worker-death path; final best-effort reroute of its buffered frees
                except Exception:
                    pass

    def _kill_worker_locked(self, worker: WorkerHandle):
        worker.dead = True
        self._conn_to_worker.pop(worker.conn, None)
        self._workers_by_hex.pop(worker.worker_id.hex(), None)
        worker.node.all_workers.pop(id(worker), None)
        self.worker_funcs.pop(id(worker), None)
        # Ship anything still buffered (frees, steals) before the kill;
        # whatever cannot be delivered gets its store-side fallback.
        try:
            worker.flush_buffered()
        except Exception:
            pass
        self._reroute_dead_worker_frees_locked(worker)
        try:
            worker.send(("kill",))  # noqa: RTL604 -- death path: kill must be ordered after the final flush on this conn
        except Exception:
            pass
        try:
            worker.conn.close()
        except Exception:
            pass

    def _on_worker_death(self, worker: WorkerHandle):
        with self.lock:
            if worker.dead:
                # A failed flush can re-buffer messages AFTER the first
                # death pass drained them (reader-thread EOF and sender-
                # thread send failure race): drain again so rerouted
                # frees are never lost.  Idempotent.
                self._reroute_dead_worker_frees_locked(worker)
                return
            worker.dead = True
            self._conn_to_worker.pop(worker.conn, None)
            self._workers_by_hex.pop(worker.worker_id.hex(), None)
            worker.node.all_workers.pop(id(worker), None)
            self.worker_funcs.pop(id(worker), None)
            self._reroute_dead_worker_frees_locked(worker)
            for key, lst in worker.node.idle_workers.items():
                if worker in lst:
                    lst.remove(worker)
            # Workers this one had leased for direct push return to the
            # pool (their direct conns EOF on their own).
            for node in self.nodes.values():
                for w in list(node.all_workers.values()):
                    if w.client_lease is worker:
                        w.client_lease = None
                        if not w.dead:
                            self._end_lease_locked(w)
            if worker.client_lease is not None \
                    and not worker.client_lease.dead \
                    and self.config.decentralized_dispatch:
                # This worker was leased OUT and died (node death rides
                # the same path — the agent's death handler drives it):
                # revoke explicitly so the holder reroutes its pushed
                # specs now instead of waiting on a direct-conn EOF.
                # Rides the conflation sender like every control-plane
                # notification.
                self.lease_revocations += 1
                self._queue_send(worker.client_lease,
                                 ("lease_revoke",
                                  [worker.worker_id.hex()]))
            worker.client_lease = None
            # Pending-export shells this worker owed a completion for:
            # the owner is gone, fail them (owner-death semantics).
            for oid, st in list(self.objects.items()):
                if st.exporter is worker and st.status == PENDING:
                    # OwnerDiedError (non-reconstructable): the exporter
                    # was the metadata authority; its lineage died too.
                    err = (protocol.ERROR, serialization.dumps_inline(  # noqa: RTL402 -- cold worker-death path; constant-sized error payload
                        exc.OwnerDiedError(
                            object_id=oid.hex(),
                            owner=worker.worker_id.hex(),
                            phase="export")))
                    st.exporter = None
                    self._complete_object_locked(oid, err, False)
            if worker.actor_id is not None:
                self._on_actor_worker_death(worker)
                return
            inflight = list(worker.inflight.values())
            worker.inflight.clear()
            self._end_lease_locked(worker)
            for rec in inflight:
                # Every task pipelined onto the dead worker retries
                # elsewhere (reference: task retries by the owner,
                # task_manager.h:174).
                if rec.retries_left > 0 and not rec.cancelled:
                    rec.retries_left -= 1
                    rec.dispatched = False
                    rec.worker = None
                    self.tasks[rec.spec["task_id"]] = rec
                    self._enqueue_pending_locked(rec)
                else:
                    self.tasks.pop(rec.spec["task_id"], None)
                    if rec.cancelled:
                        err = exc.TaskCancelledError(
                            rec.spec.get("name", "task"))
                    elif worker.oom_killed:
                        err = exc.OutOfMemoryError(
                            f"Task {rec.spec.get('name', 'task')} was "
                            f"killed by the memory monitor (node memory "
                            f"over threshold) and has no retries left")
                    else:
                        err = exc.WorkerCrashedError(
                            f"Worker died executing "
                            f"{rec.spec.get('name', 'task')}")
                    self._fail_task_locked(rec, err)
            self._dispatch_locked()

    def _on_actor_worker_death(self, worker: WorkerHandle):
        actor = self.actors.get(worker.actor_id)
        if actor is None:
            return
        # The actor held its creation lease for life; return it (resources,
        # PG bundle share, TPU chips).
        self._end_lease_locked(worker)
        req = actor.options.get("resources") or {"CPU": 1.0}
        err = exc.ActorDiedError(
            f"Actor {worker.actor_id.hex()} died (worker exit)")
        will_restart = actor.restarts_left != 0 and not self._stopped
        # In-flight method calls: replayed onto the restarted actor per
        # max_task_retries (at-least-once — the call may have partially
        # executed before the death, exactly the reference's contract),
        # else failed with ActorDiedError.  Queued-but-undispatched
        # calls always survive the restart (they never reached the dead
        # worker).  Worker/node death is a SYSTEM failure: it alone
        # decrements the replay budget.
        replay: List[TaskRecord] = []
        mtr = actor.options.get("max_task_retries", 0)
        for tid_bin, rec in list(actor.inflight.items()):
            if (will_restart and self.config.recovery and mtr != 0
                    and (mtr < 0 or rec.retries_left > 0)
                    and not rec.cancelled):
                if rec.retries_left > 0:
                    rec.retries_left -= 1
                rec.dispatched = False
                rec.worker = None
                replay.append(rec)
            else:
                self._fail_task_locked(rec, err)
        actor.inflight.clear()
        actor.worker = None
        if will_restart:
            if actor.restarts_left > 0:
                actor.restarts_left -= 1
            actor.status = RESTARTING
            if self.config.recovery:
                self.actor_restarts += 1
            # Replayed calls go BACK TO THE FRONT in their original send
            # order, ahead of anything queued behind them.
            for rec in reversed(replay):
                actor.queue.appendleft(rec)
            spec = {
                "task_id": new_task_id().binary(),
                "func_id": actor.func_id,
                "args": actor.init_args,
                "kwargs": actor.init_kwargs,
                "num_returns": 1,
                "name": "actor.__restart__",
                "resources": req,
                "scheduling_strategy": actor.options.get(
                    "scheduling_strategy"),
            }
            rec = TaskRecord(spec, req, 0)
            rec.is_actor_creation = True
            rec.actor_id = actor.actor_id
            strategy = spec.get("scheduling_strategy")
            if strategy and strategy[0] == "placement_group":
                rec.pg_id = strategy[1]
                rec.bundle_index = strategy[2]
            tid = TaskID(spec["task_id"])
            self.objects[tid.object_id(0)] = ObjectState(tid)
            self.tasks[spec["task_id"]] = rec
            self._enqueue_pending_locked(rec)
            self._dispatch_locked()
        else:
            actor.status = DEAD
            actor.death_cause = err
            self._gcs_dirty += 1
            self._fail_actor_queue_locked(actor, err)
            self._free_checkpoint_locked(actor)
            # The lease just returned the actor's resources: anything
            # waiting on capacity (pending tasks, parked client leases)
            # must get a dispatch pass — without this, a task submitted
            # while the actor held the last slot pends forever.
            self._dispatch_locked()

    def _ck_home_dying_locked(self, descr) -> bool:
        """Whether a checkpoint descriptor is homed on a store that is
        draining or already gone — state that dies with its node and
        must not displace a safely-homed checkpoint."""
        if descr is None or descr[0] not in (protocol.SHM,
                                             protocol.SPILLED) \
                or len(descr) <= 3:
            return False
        home = descr[3]
        if home == self.store_id:
            return False
        node = self._node_for_store_locked(home)
        return node is None or not node.alive or node.draining

    def _free_checkpoint_locked(self, actor: Optional[ActorState],
                                descr=None):
        """Unlink a checkpoint's storage (the superseded one on refresh,
        the last one at actor death).  Checkpoint segments live outside
        the object table, so their lifecycle is managed here: home-store
        routed like free_remote."""
        if descr is None:
            if actor is None:
                return
            descr, actor.checkpoint = actor.checkpoint, None
        if descr is None or descr[0] not in (protocol.SHM,
                                             protocol.SPILLED):
            return
        home = descr[3] if len(descr) > 3 else self.store_id
        if home == self.store_id:
            try:
                if descr[0] == protocol.SPILLED:
                    os.unlink(descr[1])
                else:
                    self.shm.unlink(descr[1], descr[2], reusable=False)
            except Exception:
                pass
        else:
            agent = self._agents.get(home)
            if agent is not None and not agent.dead:
                try:
                    agent.send(("unlink_segment", descr[1], descr[2]))  # noqa: RTL604 -- checkpoint GC is rare; one small control frame per freed ckpt
                except Exception:
                    pass

    # ----------------------------------------------------- memory monitor --
    def _memory_monitor_loop(self):
        """Kill one task worker per interval while node memory stays
        above the threshold (reference: memory_monitor.h sampling +
        worker_killing_policy_group_by_owner.cc — newest retriable task
        first, so long-running work survives and the retry is cheap)."""
        from ray_tpu._private import memmon

        cfg = self.config
        while not self._stopped:
            time.sleep(cfg.memory_monitor_interval_s)
            try:
                frac = memmon.memory_usage_fraction(
                    cfg.memory_monitor_test_file)
            except Exception:
                continue
            if frac >= cfg.memory_monitor_threshold:
                # This loop samples HEAD-node memory: victims must be
                # head-local (remote nodes sample via their agent's
                # oom_pressure, scoped the same way).
                self._oom_kill_one(frac, node=self.head_node)

    def _oom_kill_one(self, frac: float, node: Optional[NodeState] = None):
        """Pick and kill the newest-dispatched plain-task worker (actors
        and idle workers are never victims); its tasks retry via the
        normal death path, typed OutOfMemoryError when retries run out."""
        victim = None
        with self.lock:
            nodes = [node] if node is not None else list(
                self.nodes.values())
            best = -1.0
            for nd in nodes:
                for w in nd.all_workers.values():
                    if (w.dead or w.oom_killed or w.actor_id is not None
                            or not w.inflight):
                        continue
                    if any(rec.is_actor_creation
                           for rec in w.inflight.values()):
                        # actor_id is only set AFTER __init__ returns:
                        # without this check the monitor would target
                        # actors mid-creation (peak memory = exactly
                        # when pressure fires), inverting the
                        # actors-are-never-victims policy.
                        continue
                    if w.last_dispatch_ts > best:
                        best = w.last_dispatch_ts
                        victim = w
            if victim is not None:
                victim.oom_killed = True
        if victim is None:
            return
        print(f"[ray_tpu] memory monitor: node usage {frac:.0%} >= "
              f"{self.config.memory_monitor_threshold:.0%}, killing "
              f"worker {victim.worker_id.hex()[:12]} "
              f"({len(victim.inflight)} task(s) will retry)",
              file=sys.stderr)
        if victim.proc is not None:
            try:
                victim.proc.terminate()
            except Exception:
                pass
        elif victim.node.agent is not None and not victim.node.agent.dead:
            try:
                victim.node.agent.send(
                    ("kill_worker", victim.worker_id.hex()))
            except Exception:
                pass

    # -------------------------------------------------------- log monitor --
    def _record_worker_lines(self, worker_id_hex: str, lines, node=""):
        # Ring mutation under the lock: state_query("worker_log")
        # iterates these structures under the same lock.
        with self.lock:
            ring = self._worker_logs.setdefault(worker_id_hex,
                                                deque(maxlen=1000))
            ring.extend(lines)
        if self.config.log_to_driver:
            prefix = f"(worker={worker_id_hex[:8]}" + (
                f" node={node[:8]})" if node else ")")
            for ln in lines:
                print(f"{prefix} {ln}", file=sys.stderr)

    def _log_monitor_loop(self):
        """Tail head-local worker log files into per-worker rings and the
        driver's stderr (reference: log_monitor.py — file tailing with
        (pid=, ip=) prefixes; remote nodes' agents ship their lines via
        ("worker_logs", ...) instead)."""
        from ray_tpu._private.logtail import tail_worker_logs

        log_dir = os.path.join(self._sock_dir, "logs")
        offsets: Dict[str, int] = {}
        partial: Dict[str, bytes] = {}
        while not self._stopped:
            time.sleep(0.5)
            for wid, lines in tail_worker_logs(log_dir, offsets, partial):
                self._record_worker_lines(wid, lines)

    # -------------------------------------------------------- suspicion --
    def _suspicion_loop(self):
        """Head-side gray-failure detector (reference:
        gcs_health_check_manager.h — initial delay / timeout / period /
        failure threshold; HotOS'17 gray failure: DIFFERENTIAL
        observation, this peer's link to us, not its process table).

        Every live agent and worker is expected to message us at least
        once per ``health_check_period_s`` (the heartbeat floor rides
        under their existing periodic traffic).  Silence past
        ``health_check_timeout_s`` marks the peer SUSPECT (counted) and
        starts probing (``hc_probe`` — answered by the peer's reader
        thread even while it computes); ``health_check_failure_threshold``
        unanswered probes declare it DEAD and feed the EXISTING death
        path — lease revocation, lineage reconstruction, drain
        bookkeeping — exactly as a clean kill would."""
        cfg = self.config
        timeout = cfg.health_check_timeout_s
        period = cfg.health_check_period_s
        threshold = max(1, cfg.health_check_failure_threshold)
        tick = max(0.1, min(period, timeout / 2.0 or period) / 2.0)
        # Initial grace: a freshly-booted cluster's peers get extra slack
        # before their first deadline (boot + env build + JIT warmup).
        initial = cfg.health_check_initial_delay_s
        time.sleep(min(initial, 2.0) if initial > 0 else tick)
        while not self._stopped:
            time.sleep(tick)
            now = time.monotonic()
            probes = []   # (send_fn, peer) pairs, fired outside the lock
            dead_agents = []
            dead_workers = []
            with self.lock:
                for agent in list(self._agents.values()):
                    if agent.dead or agent.node is None:
                        continue
                    if "hc_probe" not in tuple(
                            agent.info.get("agent_caps") or ()):
                        continue  # old agent: never probed (PR-3 rule)
                    self._suspect_step_locked(agent, now, timeout,
                                              period, threshold,
                                              probes, dead_agents)
                for node in self.nodes.values():
                    for w in node.all_workers.values():
                        if (w.dead or w.conn is None
                                or not w.ready.is_set()
                                or w.env_key == "client"):
                            continue
                        self._suspect_step_locked(w, now, timeout,
                                                  period, threshold,
                                                  probes, dead_workers)
            for peer in probes:
                # Try-lock, not send(): a dispatcher blocked mid-send
                # to this very peer (wedged reader, full buffer) holds
                # send_lock — the probe must not wedge the suspicion
                # thread with it.  The miss was already counted; an
                # unsendable probe is just a confirmed miss.
                if not peer.send_lock.acquire(timeout=0.5):
                    continue
                try:
                    protocol.send(peer.conn, ("hc_probe", 0))
                except Exception:
                    pass  # a failed probe send is itself a miss
                finally:
                    peer.send_lock.release()
            for agent in dead_agents:
                print(f"[ray_tpu] failure detection: node "
                      f"{agent.node.node_id.hex()[:12]} declared DEAD "
                      f"after {threshold} missed probes "
                      f"(silent {now - agent.last_seen:.1f}s)",
                      file=sys.stderr)
                try:
                    # Shutdown frees a reader parked inside a stalled
                    # recv (close alone cannot wake it); it exits via
                    # the idempotent death path.
                    protocol.shutdown_conn(agent.conn)
                    agent.conn.close()
                except Exception:
                    pass
                # Drive death handling NOW, like chaos.kill_agent —
                # don't depend on the reader waking at all.
                self._on_agent_death(agent)
            for w in dead_workers:
                print(f"[ray_tpu] failure detection: worker "
                      f"{w.worker_id.hex()[:12]} declared DEAD after "
                      f"{threshold} missed probes",
                      file=sys.stderr)
                conn = w.conn
                self._on_worker_death(w)
                if conn is not None:
                    try:
                        protocol.shutdown_conn(conn)
                        conn.close()
                    except Exception:
                        pass

    def _suspect_step_locked(self, peer, now, timeout, period, threshold,
                             probes, dead):
        """One suspicion-machine step for one peer (WorkerHandle or
        AgentHandle — both carry last_seen/hc_* state).  Appends to
        ``probes``/``dead`` for the caller to act on OUTSIDE the lock."""
        silence = now - peer.last_seen
        if silence <= timeout:
            if peer.hc_suspect:
                peer.hc_suspect = False  # spoke again: fully absolved
            peer.hc_misses = 0
            return
        if not peer.hc_suspect:
            peer.hc_suspect = True
            peer.hc_misses = 0
            peer.hc_probe_ts = 0.0
            self.suspected_nodes += 1
        if now - peer.hc_probe_ts >= period:
            peer.hc_probe_ts = now
            peer.hc_misses += 1
            if peer.hc_misses > threshold:
                dead.append(peer)
            else:
                probes.append(peer)

    # ------------------------------------------------------------- reaper --
    def _reap_loop(self):
        while not self._stopped:
            time.sleep(self.config.health_check_period_s)
            now = time.monotonic()
            dead_pending = []
            with self.lock:
                if self.config.decentralized_dispatch \
                        and self.config.lease_ttl_s > 0:
                    # Expired client leases: the holder stopped renewing
                    # (died or hung mid-push).  Pushed-task state is
                    # invisible to the head, so the worker is RETIRED,
                    # not pooled — holder-side retries cover its queue,
                    # the same semantics as worker death.
                    expired = [
                        w for node in self.nodes.values()
                        for w in node.all_workers.values()
                        if w.client_lease is not None and not w.dead
                        and w.lease_expiry is not None
                        and now > w.lease_expiry]
                    for w in expired:
                        lessee = w.client_lease
                        w.client_lease = None
                        self.lease_revocations += 1
                        if lessee is not None and not lessee.dead:
                            self._queue_send(
                                lessee, ("lease_revoke",
                                         [w.worker_id.hex()]))
                        self._end_lease_locked(w, reap=True)
                    if expired:
                        self._request_dispatch_locked()
                for node in self.nodes.values():
                    for key, lst in node.idle_workers.items():
                        keep = []
                        for w in lst:
                            if (now - w.idle_since >
                                    self.config.idle_worker_timeout_s):
                                self._kill_worker_locked(w)
                            else:
                                keep.append(w)
                        node.idle_workers[key] = keep
                # Workers that died (or hung) before dialing back.
                for wid, w in list(self._pending_workers.items()):
                    # Agent-spawned workers have no local proc handle;
                    # their crash shows as a start timeout.
                    crashed = (w.proc is not None
                               and w.proc.poll() is not None)
                    timed_out = (now - w.spawned_at >
                                 self.config.worker_start_timeout_s)
                    if crashed or timed_out:
                        self._pending_workers.pop(wid, None)
                        dead_pending.append(w)
            for w in dead_pending:
                if w.proc is not None:
                    try:
                        w.proc.terminate()
                    except Exception:
                        pass
                elif w.node.agent is not None and not w.node.agent.dead:
                    try:
                        w.node.agent.send(
                            ("kill_worker", w.worker_id.hex()))
                    except Exception:
                        pass
                self._on_worker_death(w)

    # ----------------------------------------------------------- KV store --
    def kv_put(self, key: bytes, value: bytes, namespace="default",
               overwrite=True) -> bool:
        with self.lock:
            ns = self.kv.setdefault(namespace, {})
            if not overwrite and key in ns:
                return False
            ns[key] = value
            self._gcs_dirty += 1
            return True

    def kv_get(self, key: bytes, namespace="default"):
        with self.lock:
            return self.kv.get(namespace, {}).get(key)

    def kv_del(self, key: bytes, namespace="default"):
        with self.lock:
            self._gcs_dirty += 1
            return self.kv.get(namespace, {}).pop(key, None) is not None

    def kv_keys(self, prefix: bytes = b"", namespace="default"):
        with self.lock:
            return [k for k in self.kv.get(namespace, {})
                    if k.startswith(prefix)]

    # ------------------------------------------------------------ cancel --
    def poll_events(self, topic: str) -> list:
        """Drain pubsub payloads for a topic (driver side)."""
        with self.lock:
            q = self.events.get(topic)
            if not q:
                return []
            out = list(q)
            q.clear()
            return out

    def add_event_listener(self, topic: str, cb) -> None:
        """Fire ``cb()`` (no payload — consumers drain via poll_events)
        whenever a worker publishes on ``topic``.  The autoscaler's
        serve-event trigger: a controller scale event wakes the
        reconcile loop immediately instead of waiting out its tick."""
        with self.lock:
            self._event_listeners.setdefault(topic, []).append(cb)

    def remove_event_listener(self, topic: str, cb) -> None:
        """Unregister a listener added by add_event_listener (a stopped
        autoscaler must not stay referenced — and woken — forever)."""
        with self.lock:
            lst = self._event_listeners.get(topic)
            if lst is not None:
                try:
                    lst.remove(cb)
                except ValueError:
                    pass
                if not lst:
                    self._event_listeners.pop(topic, None)

    def cancel_task(self, object_id: ObjectID, force=False):
        with self.lock:
            st = self.objects.get(object_id)
            if st is None or st.task_id is None:
                return
            rec = self.tasks.get(st.task_id.binary())
            if rec is None:
                return
            rec.cancelled = True
            if not rec.dispatched:
                # Drop the record from its scheduling-class queue now —
                # dispatch stops at an unplaceable class head, so cancelled
                # records behind it would otherwise be retained forever.
                q = self.pending_tasks.get(rec.sched_key
                                           or self._sched_class(rec))
                if q is not None:
                    try:
                        q.remove(rec)
                    except ValueError:
                        pass
                self._fail_task_locked(rec, exc.TaskCancelledError(
                    rec.spec.get("name", "task")))
            elif force and rec.worker is not None:
                rec.retries_left = 0
                w = rec.worker
                if w.actor_id is not None or not w.inflight:
                    # Actor worker (no pipelined plain tasks) or nothing to
                    # rescue: kill immediately.
                    try:
                        w.proc.terminate()
                    except Exception:
                        pass
                else:
                    # Steal back every unstarted pipelined task first; the
                    # "stolen" handler terminates the process only if the
                    # victim had actually started (bystanders would
                    # otherwise burn retries or die as WorkerCrashedError).
                    w.pending_force_kill = rec.spec["task_id"]
                    try:
                        self._queue_send(w, ("steal", 0,
                                             list(w.inflight.keys())))
                    except Exception:
                        try:
                            w.proc.terminate()
                        except Exception:
                            pass
                    # A wedged worker (GIL held in C code) never answers
                    # the steal — the whole point of force-kill.  Fall back
                    # to terminate if no "stolen" reply resolves it in time.
                    def _force_kill_fallback(w=w):
                        with self.lock:
                            if w.pending_force_kill is None or w.dead:
                                return
                            w.pending_force_kill = None
                        try:
                            w.proc.terminate()
                        except Exception:
                            pass
                    t = threading.Timer(2.0, _force_kill_fallback)
                    t.daemon = True
                    t.start()
            elif rec.worker is not None:
                # Pipelined onto a worker but possibly not started: try to
                # steal it back; the "stolen" handler sees cancelled=True
                # and fails it.  Already-started tasks are uncancellable
                # without force (reference semantics).
                try:
                    self._queue_send(rec.worker,
                                     ("steal", 0, [rec.spec["task_id"]]))
                except Exception:
                    pass

    # ---------------------------------------------------------- shutdown --
    def shutdown(self):
        if self._stopped:
            return
        self._stopped = True
        self._gcs_stop.set()  # wake the snapshot loop out of its wait
        if self.config.gcs_snapshot_path:
            # Final snapshot while the tables are still live: a clean
            # shutdown must leave a restartable image even if the last
            # periodic write raced this exit.
            try:
                self._snapshot_gcs(clean=True)
            except Exception:
                with self.lock:
                    self.gcs_snapshot_failures += 1
        self._sender_event.set()  # unblock the conflation sender's exit
        self._dispatch_event.set()  # unblock the dispatcher's exit
        with self.lock:
            workers = [w for n in self.nodes.values()
                       for w in list(n.all_workers.values())]
            for n in self.nodes.values():
                for lst in n.idle_workers.values():
                    workers.extend(lst)
        with self.lock:
            workers.extend(self._pending_workers.values())
            self._pending_workers.clear()
        for w in set(workers):
            try:
                w.send(("kill",))
            except Exception:
                pass
        deadline = time.monotonic() + 2.0
        for w in set(workers):
            try:
                w.proc.wait(max(0.05, deadline - time.monotonic()))
            except Exception:
                try:
                    w.proc.terminate()
                except Exception:
                    pass
        try:
            self._listener.close()
            self._tcp_listener.close()
        except Exception:
            pass
        try:
            self._obj_listener.close()
        except Exception:
            pass
        try:
            self._puller.close()
        except Exception:
            pass
        for agent in list(self._agents.values()):
            try:
                agent.send(("shutdown",))
                agent.conn.close()
            except Exception:
                pass
        self.shm.cleanup()
        # Worker-created segments (task results still referenced at exit)
        # are in this session's namespace but not in the driver store's
        # created-set; sweep them by prefix.
        import glob as _glob

        for path in _glob.glob(os.path.join(
                self.shm._dir, f"rtpu-{self.session_id}-*")):
            try:
                os.unlink(path)
            except OSError:
                pass
        try:
            import shutil as _shutil

            _shutil.rmtree(self.spill_dir, ignore_errors=True)
        except Exception:
            pass
        try:
            import shutil

            shutil.rmtree(self._sock_dir, ignore_errors=True)
        except Exception:
            pass

    # ------------------------------------------------------- introspection --
    def cluster_resources(self):
        with self.lock:
            total: Dict[str, float] = {}
            for n in self.nodes.values():
                if not n.alive:
                    continue
                for k, v in n.resources.items():
                    total[k] = total.get(k, 0.0) + v
            return total

    def available_resources(self):
        with self.lock:
            total: Dict[str, float] = {}
            for n in self.nodes.values():
                if not n.alive:
                    continue
                for k, v in n.available.items():
                    total[k] = total.get(k, 0.0) + v
            return total

    def pending_resource_demand(self) -> List[Dict[str, float]]:
        """Resource shapes of everything queued-but-unplaced: the
        autoscaler's scale-up signal (reference: pending demand reported to
        the monitor, resource_demand_scheduler.py)."""
        with self.lock:
            out: List[Dict[str, float]] = []
            for q in self.pending_tasks.values():
                for rec in q:
                    if not rec.dispatched and not rec.cancelled:
                        out.append(dict(rec.requirements))
            for pg in self.pending_pgs:
                out.extend(dict(b) for b in pg.bundles)
            # Lease starvation: client lease requests PARKED for lack of
            # capacity are demand the task queues never show — the
            # holder's tasks wait inside its own DirectCaller, invisible
            # here.  Feeding the parked shapes in is what lets the
            # autoscaler scale for direct-path (leased) traffic too.
            for p in self._pending_client_leases:
                if not p["lessee"].dead:
                    out.extend(dict(p["req"]) for _ in range(max(1,
                                                                 p["n"])))
            return out

    def node_activity(self) -> List[Dict[str, Any]]:
        """Per-node busy/idle for autoscaler scale-down decisions."""
        with self.lock:
            out = []
            for node in self.nodes.values():
                busy = any((w.inflight or w.actor_id is not None)
                           and not w.dead
                           for w in node.all_workers.values())
                out.append({
                    "node_id": node.node_id.hex(),
                    "alive": node.alive,
                    "is_head": node is self.head_node,
                    "busy": busy,
                    "draining": node.draining,
                    "resources": dict(node.resources),
                    "available": dict(node.available),
                })
            return out

    def state_query(self, kind: str, limit: int = 10000,
                    **filters) -> list:
        """State-observability reads over the authoritative tables
        (reference: python/ray/experimental/state/api.py:738,961,1005 —
        there an aggregator service queries GCS + raylets; here the tables
        are driver-resident so this is a read under the lock)."""
        if kind == "nodes":
            return self.list_nodes()[:limit]
        if kind == "actors":
            with self.lock:
                out = []
                for aid, a in self.actors.items():
                    out.append({
                        "actor_id": aid.hex(),
                        "state": a.status,
                        "name": a.name,
                        "class_name": a.options.get("class_name"),
                        "node_id": (a.node.node_id.hex()
                                    if a.node is not None else None),
                        "pending_tasks": len(a.queue) + len(a.inflight),
                        "restarts_left": a.restarts_left,
                    })
                return out[:limit]
        if kind == "tasks":
            # task_events is a bounded ring (latest event per id wins); the
            # LIVE task table overlays it so queued/running tasks are
            # always visible even if their events were evicted.
            with self.lock:
                latest: Dict[str, dict] = {}
                for ev in self.task_events:
                    latest[ev["task_id"]] = ev
                for tid_bin, rec in self.tasks.items():
                    tid = tid_bin.hex()
                    st = "RUNNING" if rec.dispatched else "PENDING"
                    cur = latest.get(tid)
                    if cur is None or cur["state"] in ("SUBMITTED",
                                                      "PENDING"):
                        latest[tid] = {"task_id": tid,
                                       "name": rec.spec.get("name"),
                                       "state": st,
                                       "time": time.time()}
                out = [dict(ev) for ev in latest.values()]
            return out[:limit]
        if kind == "objects":
            with self.lock:
                status_names = {PENDING: "PENDING", READY: "READY",
                                ERRORED: "ERRORED"}
                out = []
                for oid, st in self.objects.items():
                    d = st.descr
                    out.append({
                        "object_id": oid.hex(),
                        "state": status_names.get(st.status, "?"),
                        "kind": (d[0] if d is not None else None),
                        "size": (d[2] if d is not None
                                 and d[0] in (protocol.SHM,
                                              protocol.SPILLED)
                                 else None),
                        "local_refs": st.local_refs,
                        "worker_refs": st.worker_refs,
                        "pins": st.pins,
                    })
                return out[:limit]
        if kind == "workers":
            with self.lock:
                out = []
                for node in self.nodes.values():
                    for w in node.all_workers.values():
                        out.append({
                            "worker_id": w.worker_id.hex(),
                            "node_id": node.node_id.hex(),
                            "alive": not w.dead,
                            "actor_id": (w.actor_id.hex()
                                         if w.actor_id else None),
                            "inflight": len(w.inflight),
                            "blocked": w.blocked,
                        })
                return out[:limit]
        if kind == "placement_groups":
            with self.lock:
                return [{
                    "placement_group_id": pg.pg_id.hex(),
                    "name": pg.name,
                    "strategy": pg.strategy,
                    "bundles": list(pg.bundles),
                    "reserved": [n.hex() if n is not None else None
                                 for n in pg.reserved],
                    "removed": pg.removed,
                } for pg in self.placement_groups.values()][:limit]
        if kind == "spans":
            with self.lock:
                n = len(self.task_spans)
                return list(itertools.islice(self.task_spans,
                                             max(0, n - limit), None))
        if kind == "worker_log":
            # filters: worker_id (hex prefix ok), tail (line count).
            prefix = filters.get("worker_id", "")
            tail = int(filters.get("tail", 200))
            with self.lock:
                out = []
                for wid, ring in self._worker_logs.items():
                    if wid.startswith(prefix):
                        out.append({"worker_id": wid,
                                    "lines": list(ring)[-tail:]})
            return out[:limit]
        if kind == "transfer_stats":
            return [self.transfer_stats()]
        if kind == "handler_stats":
            with self._handler_stats_lock:
                return [{
                    "handler": tag, "count": s[0],
                    "total_ms": round(s[1] * 1e3, 3),
                    "mean_us": round(s[1] / s[0] * 1e6, 1),
                    "max_ms": round(s[2] * 1e3, 3),
                } for tag, s in sorted(self._handler_stats.items(),
                                       key=lambda kv: -kv[1][1])][:limit]
        raise ValueError(f"unknown state query kind {kind!r}")

    def transfer_stats(self) -> Dict[str, int]:
        """Data-plane + locality counters in one snapshot: the scheduler's
        locality accounting plus the aggregated worker-side prefetch/
        dedup deltas, next to the head's own relay fallbacks."""
        # The head process's OWN deadline-core counters (its puller /
        # relay stalls) merge with the worker/client deltas aggregated
        # below — one cluster-wide number per counter.
        head_net = protocol.net_stats()
        # Same pattern for the push-shuffle coordinator: when the
        # driver IS this head process, its map/merge/hedge work counts
        # in the shuffle module's process-local registry, not in any
        # worker's xfer_stats delta.  Lazy module lookup: never imported
        # (switch off, or no shuffle ran) means all-zero.
        shuffle_mod = sys.modules.get("ray_tpu.data.shuffle")
        head_shuf = (shuffle_mod.shuffle_stats() if shuffle_mod is not None
                     else {})
        # And for the distributed-training planes: the PipelineTrainer
        # driver and IMPALA's learner-side loader usually ARE this head
        # process, so their counters live in the train module's
        # process-local registry, not in any worker delta.
        train_mod = sys.modules.get("ray_tpu.train.pipeline_actors")
        head_train = (train_mod.train_stats() if train_mod is not None
                      else {})
        with self.lock:
            return {
                "shuffle_pushed_bytes":
                    self.shuffle_pushed_bytes
                    + head_shuf.get("shuffle_pushed_bytes", 0),
                "shuffle_merges":
                    self.shuffle_merges
                    + head_shuf.get("shuffle_merges", 0),
                "shuffle_spills":
                    self.shuffle_spills
                    + head_shuf.get("shuffle_spills", 0),
                "shuffle_hedges":
                    self.shuffle_hedges
                    + head_shuf.get("shuffle_hedges", 0),
                "microbatch_pushes":
                    self.microbatch_pushes
                    + head_train.get("microbatch_pushes", 0),
                "stage_restarts":
                    self.stage_restarts
                    + head_train.get("stage_restarts", 0),
                "learner_queue_stalls":
                    self.learner_queue_stalls
                    + head_train.get("learner_queue_stalls", 0),
                "suspected_nodes": self.suspected_nodes,
                "stall_timeouts":
                    self.stall_timeouts + head_net["stall_timeouts"],
                "net_retries":
                    self.net_retries + head_net["net_retries"],
                "hedged_fetches":
                    self.hedged_fetches + head_net["hedged_fetches"],
                "locality_hits": self.locality_hits,
                "locality_misses": self.locality_misses,
                "locality_bytes_saved": self.locality_bytes_saved,
                "prefetch_hit_bytes": self.prefetch_hit_bytes,
                "prefetch_waste_bytes": self.prefetch_waste_bytes,
                "deduped_pulls": self.deduped_pulls,
                "brokered_parts": self.brokered_parts,
                "relayed_segments": self.relayed_segments,
                "direct_puts": self.direct_puts,
                "direct_put_bytes": self.direct_put_bytes,
                "brokered_put_parts": self.brokered_put_parts,
                "lease_grants": self.lease_grants,
                "leased_submits": self.leased_submits,
                "spillbacks": self.spillbacks,
                "lease_revocations": self.lease_revocations,
                "head_brokered_submits": self.head_brokered_submits,
                "reconstructions": self.reconstructions,
                "reconstruction_failures": self.reconstruction_failures,
                "actor_restarts": self.actor_restarts,
                "chaos_kills": self.chaos_kills,
                "gcs_snapshots": self.gcs_snapshots,
                "gcs_snapshot_failures": self.gcs_snapshot_failures,
                "reconnected_nodes": self.reconnected_nodes,
                "reregistered_workers": self.reregistered_workers,
                "adopted_actors": self.adopted_actors,
                "preemptions": self.preemptions,
                "drains_completed": self.drains_completed,
                "drain_timeouts": self.drain_timeouts,
                "objects_migrated": self.objects_migrated,
            }

    def list_nodes(self):
        with self.lock:
            return [
                {"node_id": n.node_id.hex(), "alive": n.alive,
                 "resources": dict(n.resources),
                 "available": dict(n.available), "labels": dict(n.labels)}
                for n in self.nodes.values()
            ]


