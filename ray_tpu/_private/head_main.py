"""Standalone head process — the GCS-server-analog entry point.

Reference: ``src/ray/gcs/gcs_server/gcs_server_main.cc`` — the reference
runs its cluster metadata service as a dedicated process precisely so it
can die and restart under the cluster.  This entry boots a driverless
head (``ray_tpu.init`` with env-provided resources/config), optionally
runs a bootstrap script in-process, and then parks; agents and clients
dial its fixed TCP port.  With ``gcs_snapshot_path`` + ``listen_port`` +
``authkey_hex`` configured, killing this process and re-running it with
``gcs_restore`` is the head-failover drill the chaos harness
(``Cluster(external_head=True)`` + ``ChaosController.kill_head``)
automates.

Env contract (all optional unless noted):

- ``RAY_TPU_HEAD_NUM_CPUS`` / ``RAY_TPU_HEAD_NUM_TPUS`` — head node
  resources (default 0: the head schedules only onto agents).
- ``RAY_TPU_HEAD_SYSTEM_CONFIG`` — JSON ``_system_config`` dict; the
  failover drill sets listen_port/authkey_hex/gcs_snapshot_path here.
- ``RAY_TPU_HEAD_SCRIPT`` — python source exec'd after init with
  ``ray``/``rt`` in scope (test bootstrap: deploy serve apps, create
  named actors in-head).
- ``RAY_TPU_CHAOS`` — ``head:<point>:<n>`` rules arm deterministic
  self-kills at head syncpoints (``head:snapshot:n``,
  ``head:dispatch:n``); workers and agents have armed theirs since
  PR 9, the head process now does too.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time


def main():
    from ray_tpu._private import recovery

    # Arm head-role chaos rules BEFORE the runtime boots so boot-path
    # syncpoints (snapshot/dispatch during restore) can fire too.
    recovery.maybe_arm_env_chaos("head")

    import ray_tpu

    num_cpus = int(os.environ.get("RAY_TPU_HEAD_NUM_CPUS", "0") or 0)
    num_tpus = int(os.environ.get("RAY_TPU_HEAD_NUM_TPUS", "0") or 0)
    cfg = json.loads(os.environ.get("RAY_TPU_HEAD_SYSTEM_CONFIG") or "{}")
    rt = ray_tpu.init(num_cpus=num_cpus, num_tpus=num_tpus,
                      _system_config=cfg)

    script = os.environ.get("RAY_TPU_HEAD_SCRIPT")
    if script:
        exec(compile(script, "<head-script>", "exec"),  # noqa: S102 -- operator-provided bootstrap, same trust domain as this process
             {"ray": ray_tpu, "ray_tpu": ray_tpu, "rt": rt})

    def _term(*_sig):
        ray_tpu.shutdown()
        sys.exit(0)

    signal.signal(signal.SIGTERM, _term)
    # The READY line is the spawn protocol: cluster_utils waits for it
    # before letting agents/clients dial in.
    print("RAY_TPU_HEAD_READY", rt.tcp_address, flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
