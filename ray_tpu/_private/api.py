"""Public API functions: init/shutdown/get/put/wait/kill/cancel/...

Reference: ``python/ray/_private/worker.py`` — ``init`` (:1045), ``get``
(:2305), ``put``, ``wait``, ``shutdown`` (:1602) — with the same semantics
on the TPU-native runtime.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

from ray_tpu._private import api_internal
from ray_tpu._private.config import Config
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime import Runtime
from ray_tpu import exceptions as exc


def init(num_cpus: Optional[int] = None, num_tpus: Optional[int] = None,
         resources: Optional[dict] = None, namespace: str = "default",
         ignore_reinit_error: bool = False, _system_config: dict | None = None,
         address: Optional[str] = None, _authkey: Optional[str] = None,
         **_compat_kwargs):
    """Start the runtime (reference: python/ray/_private/worker.py:1045),
    or — with ``address`` — ATTACH to a running cluster in client mode
    (reference: Ray Client, ray.init("ray://...")).

    ``num_tpus`` defaults to the number of locally attached TPU chips if jax
    is importable and sees TPU devices; pass 0 to disable.
    """
    import os as _os

    if address is None:
        address = _os.environ.get("RAY_TPU_CLIENT_ADDRESS")
    if address:
        cur = api_internal.get_runtime()
        if cur is not None and getattr(cur, "is_client", False):
            # Honor the reinit contract in client mode too: never stack a
            # second connection under existing ObjectRefs.
            if ignore_reinit_error:
                return cur
            raise RuntimeError("ray_tpu.init() called twice "
                               "(pass ignore_reinit_error=True to allow).")
        from ray_tpu._private.client import client_connect

        key = _authkey or _os.environ.get("RAY_TPU_CLIENT_AUTHKEY")
        if not key:
            raise ValueError("client mode needs _authkey= or "
                             "RAY_TPU_CLIENT_AUTHKEY")
        rt = client_connect(address, bytes.fromhex(key))
        api_internal.set_global_runtime(rt)
        return rt
    rt = api_internal.get_runtime()
    if rt is not None:
        if isinstance(rt, Runtime) and not rt._stopped:
            if ignore_reinit_error:
                return rt
            raise RuntimeError("ray_tpu.init() called twice "
                               "(pass ignore_reinit_error=True to allow).")
    if num_tpus is None:
        num_tpus = _detect_tpu_chips()
    config = Config.from_env(_system_config)
    rt = Runtime(config, num_cpus=num_cpus, num_tpus=num_tpus,
                 resources=resources, job_name=namespace)
    api_internal.set_global_runtime(rt)
    return rt


def _detect_tpu_chips() -> int:
    """Count local TPU chips without initializing the TPU runtime in the
    driver (the chips belong to workers; reference analog: GPU autodetect in
    python/ray/_private/resource_spec.py)."""
    import glob
    import os

    if os.environ.get("RAY_TPU_FORCE_NUM_TPUS"):
        return int(os.environ["RAY_TPU_FORCE_NUM_TPUS"])
    # vfio devices (TPU VM) or accel nodes
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


def shutdown():
    rt = api_internal.get_runtime()
    if isinstance(rt, Runtime):
        rt.shutdown()
    elif rt is not None and getattr(rt, "is_client", False):
        rt.disconnect()
    api_internal.set_global_runtime(None)


def is_initialized() -> bool:
    rt = api_internal.get_runtime()
    return rt is not None and not getattr(rt, "_stopped", False)


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put on an ObjectRef is not allowed "
                        "(reference parity: python/ray/_private/worker.py).")
    return api_internal.require_runtime().put_object(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    rt = api_internal.require_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get_objects([refs], timeout)[0]
    if isinstance(refs, (list, tuple)):
        for r in refs:
            if not isinstance(r, ObjectRef):
                raise TypeError(
                    f"ray_tpu.get takes ObjectRefs, got {type(r).__name__}")
        return rt.get_objects(list(refs), timeout)
    raise TypeError(
        f"ray_tpu.get takes an ObjectRef or list, got {type(refs).__name__}")


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    rt = api_internal.require_runtime()
    if isinstance(refs, ObjectRef):
        raise TypeError("ray_tpu.wait takes a list of ObjectRefs")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("ray_tpu.wait got duplicate ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns > number of refs")
    return rt.wait_objects(refs, num_returns, timeout, fetch_local)


def kill(actor_handle, *, no_restart: bool = True):
    from ray_tpu.actor import ActorHandle

    if not isinstance(actor_handle, ActorHandle):
        raise TypeError("ray_tpu.kill takes an ActorHandle")
    rt = api_internal.require_runtime()
    if rt.is_worker():
        rt._request(lambda rid: ("kill_actor_req", rid,
                                 actor_handle._actor_id, no_restart))
    else:
        rt.kill_actor(actor_handle._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    rt = api_internal.require_runtime()
    if rt.is_worker():
        raise NotImplementedError("cancel from inside tasks lands in v2")
    rt.cancel_task(ref.id(), force)


def cluster_resources() -> dict:
    return api_internal.require_runtime().cluster_resources()


def available_resources() -> dict:
    return api_internal.require_runtime().available_resources()


def nodes() -> List[dict]:
    return api_internal.require_runtime().list_nodes()
