"""Round benchmark: mirrors the reference's microbenchmark harness
(`python/ray/_private/ray_perf.py:93`, numbers in BASELINE.md) on this
framework's core runtime, and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

value/vs_baseline = geometric mean of (ours / reference-published) over the
core task/actor/object microbenchmarks — 1.0 is parity with the numbers the
reference repo publishes for itself (release_logs/2.3.0/microbenchmark.json).
Per-metric results go to stderr for the curious.
"""

import json
import sys
import time


# Reference-published means (BASELINE.md, release_logs/2.3.0).
BASELINE = {
    "single_client_tasks_sync": 1304.0,
    "single_client_tasks_async": 11031.0,
    "one_one_actor_calls_sync": 2142.0,
    "one_one_actor_calls_async": 8099.0,
    "one_n_actor_calls_async": 10962.0,
    "single_client_put_gigabytes": 20.4,
}


def timeit(fn, n, warmup=50):
    fn(min(warmup, n))
    t0 = time.perf_counter()
    fn(n)
    return n / (time.perf_counter() - t0)


def main():
    import ray_tpu as ray
    # 8 worker-pool CPUs for tasks + 9 actors (1 CPU each) below.
    ray.init(num_cpus=17)

    @ray.remote
    def f():
        return None

    @ray.remote
    class Actor:
        def m(self):
            return None

    results = {}

    def tasks_sync(n):
        for _ in range(n):
            ray.get(f.remote())

    results["single_client_tasks_sync"] = timeit(tasks_sync, 300, 30)

    def tasks_async(n):
        ray.get([f.remote() for _ in range(n)])

    results["single_client_tasks_async"] = timeit(tasks_async, 3000)

    a = Actor.remote()
    ray.get(a.m.remote())

    def actor_sync(n):
        for _ in range(n):
            ray.get(a.m.remote())

    results["one_one_actor_calls_sync"] = timeit(actor_sync, 1000)

    def actor_async(n):
        ray.get([a.m.remote() for _ in range(n)])

    results["one_one_actor_calls_async"] = timeit(actor_async, 3000)

    actors = [Actor.remote() for _ in range(8)]
    ray.get([b.m.remote() for b in actors])

    def one_n_async(n):
        per = n // len(actors)
        ray.get([b.m.remote() for b in actors for _ in range(per)])

    results["one_n_actor_calls_async"] = timeit(one_n_async, 4000)

    import numpy as np
    arr = np.zeros(1024 * 1024 * 100, dtype=np.uint8)  # 100 MB

    def put_gb(n):
        for _ in range(n):
            ray.put(arr)

    gb = len(arr) / 1e9
    rate = timeit(put_gb, 20, 2)
    results["single_client_put_gigabytes"] = rate * gb

    ray.shutdown()

    ratios = []
    for k, v in results.items():
        r = v / BASELINE[k]
        ratios.append(r)
        print(f"  {k}: {v:.1f} (ref {BASELINE[k]:.1f}, {r:.2f}x)",
              file=sys.stderr)
    geo = 1.0
    for r in ratios:
        geo *= r
    geo **= 1.0 / len(ratios)
    print(json.dumps({
        "metric": "core_microbench_geomean_vs_reference",
        "value": round(geo, 4),
        "unit": "x (1.0 = reference-published parity)",
        "vs_baseline": round(geo, 4),
    }))


if __name__ == "__main__":
    main()
