"""Round benchmark: core-runtime microbenchmarks mirroring the reference's
harness (`python/ray/_private/ray_perf.py:93`, numbers in BASELINE.md) plus
TPU compute benchmarks (flash attention, flagship train step) on the real
chip.  Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "tpu": {...}}

value/vs_baseline = geometric mean of (ours / reference-published) over the
core task/actor/object microbenchmarks — 1.0 is parity with the numbers the
reference repo publishes for itself (release_logs/2.3.0/microbenchmark.json).
The "tpu" dict carries device-compute numbers (tokens/s, MFU, flash-attention
timings) that the reference has no counterpart for (its release tests assert
completion, not throughput).  Per-metric results go to stderr.
"""

import json
import sys
import time


# Reference-published means (BASELINE.md, release_logs/2.3.0).
BASELINE = {
    "single_client_tasks_sync": 1304.0,
    "single_client_tasks_async": 11031.0,
    "multi_client_tasks_async": 28385.0,
    "one_one_actor_calls_sync": 2142.0,
    "one_one_actor_calls_async": 8099.0,
    "one_one_actor_calls_concurrent": 4928.0,
    "one_one_async_actor_calls_sync": 1559.0,
    "one_n_actor_calls_async": 10962.0,
    "n_n_actor_calls_async": 32387.0,
    "single_client_get_calls": 5902.0,
    "single_client_put_gigabytes": 20.4,
    "multi_client_put_gigabytes": 36.2,
    "single_client_wait_1k_refs": 5.45,
    "single_client_get_object_containing_10k_refs": 13.3,
    # Ray Client (external process driving the cluster; the reference
    # proxies through gRPC — microbenchmark.json client__* rows).
    "client_get_calls": 1190.7,
    "client_put_calls": 832.7,
    "client_put_gigabytes": 0.0457,
    "client_one_one_actor_calls_sync": 533.3,
}

# Not folded into the headline geomean: the reference's get_calls number
# measures plasma-store gets through a store RPC, while ours are in-process
# zero-copy mmap attaches — a structurally different (and much faster)
# operation, so the ratio would flatter the geomean apples-to-oranges.
NON_COMPARABLE = {"single_client_get_calls"}


def timeit(fn, n, warmup=50):
    fn(min(warmup, n))
    t0 = time.perf_counter()
    fn(n)
    return n / (time.perf_counter() - t0)


def timeit_best_of(fn, n, warmup=50, rounds=3):
    """Best-of-N with the raw per-round samples preserved.  The contended
    multi-client rows swing 2-4x on IDENTICAL code under shared-host load
    (PR 2's interleaved A/B notes); recording every sample in the round
    JSON makes that drift diagnosable from the artifact instead of
    looking like a code regression."""
    fn(min(warmup, n))
    samples = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        fn(n)
        samples.append(round(n / (time.perf_counter() - t0), 1))
    return max(samples), samples


def core_bench():
    import numpy as np

    import ray_tpu as ray
    # Actors below hold 19 CPU slots; the rest are worker-pool slots for
    # task leases (the reference harness runs on a 64-vCPU box with the
    # full core count available).
    ray.init(num_cpus=32)

    @ray.remote
    def f():
        return None

    @ray.remote
    class Actor:
        def m(self):
            return None

    @ray.remote
    class Client:
        """Driver-proxy submitting work from a worker process
        (ray_perf's 'multi client' metrics)."""

        def run_tasks(self, n):
            import ray_tpu as ray
            ray.get([f.remote() for _ in range(n)])

        def call_actor(self, target, n):
            import ray_tpu as ray
            ray.get([target.m.remote() for _ in range(n)])

        def put_bytes(self, nbytes, reps):
            import numpy as np

            import ray_tpu as ray
            # Source array allocated once per client and kept warm across
            # calls (ray_perf.py's multi-client put loop reuses one warm
            # buffer per client; a cold np.zeros would measure the
            # kernel's zero-page faulting, not the store).
            a = getattr(self, "_buf", None)
            if a is None or len(a) != nbytes:
                a = self._buf = np.ones(nbytes, dtype=np.uint8)
            for _ in range(reps):
                ray.put(a)

    results = {}
    # Raw best-of-3 samples for the contended fan-in rows, carried into
    # the round JSON next to the headline values.
    raw_samples = {}

    def tasks_sync(n):
        for _ in range(n):
            ray.get(f.remote())

    results["single_client_tasks_sync"] = timeit(tasks_sync, 300, 30)

    def tasks_async(n):
        ray.get([f.remote() for _ in range(n)])

    results["single_client_tasks_async"] = timeit(tasks_async, 3000)

    clients = [Client.remote() for _ in range(4)]

    def multi_tasks_async(n):
        per = n // len(clients)
        ray.get([c.run_tasks.remote(per) for c in clients])

    results["multi_client_tasks_async"], raw_samples[
        "multi_client_tasks_async"] = timeit_best_of(
            multi_tasks_async, 4000, 400)

    a = Actor.remote()
    ray.get(a.m.remote())

    def actor_sync(n):
        for _ in range(n):
            ray.get(a.m.remote())

    results["one_one_actor_calls_sync"] = timeit(actor_sync, 1000)

    def actor_async(n):
        ray.get([a.m.remote() for _ in range(n)])

    results["one_one_actor_calls_async"] = timeit(actor_async, 3000)

    @ray.remote
    class ThreadedActor:
        def m(self):
            return None

    ta = ThreadedActor.options(max_concurrency=4).remote()
    ray.get(ta.m.remote())

    def actor_concurrent(n):
        ray.get([ta.m.remote() for _ in range(n)])

    results["one_one_actor_calls_concurrent"] = timeit(actor_concurrent,
                                                       2000)

    @ray.remote
    class AsyncActor:
        async def m(self):
            return None

    aa = AsyncActor.remote()
    ray.get(aa.m.remote())

    def async_actor_sync(n):
        for _ in range(n):
            ray.get(aa.m.remote())

    results["one_one_async_actor_calls_sync"] = timeit(async_actor_sync,
                                                       800)

    actors = [Actor.remote() for _ in range(8)]
    ray.get([b.m.remote() for b in actors])

    def one_n_async(n):
        per = n // len(actors)
        ray.get([b.m.remote() for b in actors for _ in range(per)])

    results["one_n_actor_calls_async"] = timeit(one_n_async, 4000)

    targets = [Actor.remote() for _ in range(4)]
    ray.get([t.m.remote() for t in targets])

    def n_n_async(n):
        per = n // len(clients)
        ray.get([c.call_actor.remote(t, per)
                 for c, t in zip(clients, targets)])

    results["n_n_actor_calls_async"], raw_samples[
        "n_n_actor_calls_async"] = timeit_best_of(n_n_async, 4000, 400)

    # get calls on shm-resident objects: fresh refs each round so the
    # runtime's value cache cannot short-circuit deserialization; the puts
    # happen OUTSIDE the timed region (baseline measures gets only).
    small = np.zeros(1310720, dtype=np.uint8)  # ~1.3MB > inline cutoff
    warm = [ray.put(small) for _ in range(50)]
    for r in warm:
        ray.get(r)
    del warm
    refs = [ray.put(small) for _ in range(500)]
    t0 = time.perf_counter()
    for r in refs:
        ray.get(r)
    results["single_client_get_calls"] = 500 / (time.perf_counter() - t0)
    del refs

    arr = np.zeros(1024 * 1024 * 100, dtype=np.uint8)  # 100 MB

    def put_gb(n):
        for _ in range(n):
            ray.put(arr)

    # Best-of-3 with raw per-round samples (like the contended fan-in
    # rows): the put rows are memory-bandwidth-bound and swing with
    # shared-host load, so drift must be diagnosable from the artifact.
    gb = len(arr) / 1e9
    best, samples = timeit_best_of(put_gb, 20, 3)
    results["single_client_put_gigabytes"] = best * gb
    raw_samples["single_client_put_gigabytes"] = [
        round(s * gb, 3) for s in samples]

    def multi_put_gb(n):
        reps = n // len(clients)
        ray.get([c.put_bytes.remote(len(arr), reps) for c in clients])

    best, samples = timeit_best_of(multi_put_gb, 12, 4)
    results["multi_client_put_gigabytes"] = best * gb
    raw_samples["multi_client_put_gigabytes"] = [
        round(s * gb, 3) for s in samples]

    def wait_1k(n):
        for _ in range(n):
            refs = [f.remote() for _ in range(1000)]
            ray.wait(refs, num_returns=1000, timeout=60)

    results["single_client_wait_1k_refs"] = timeit(wait_1k, 8, 1)

    # Baseline semantics (ray_perf.py): a task builds the container once
    # outside the timed region; the metric is gets/s of an object whose
    # payload is 10k ObjectRefs (deserialize + register + drop 10k refs
    # per get).  Distinct worker-created containers per iteration so the
    # driver's value cache can't short-circuit deserialization.
    @ray.remote
    def make_box():
        import ray_tpu as ray
        return [ray.put(b"x") for _ in range(10000)]

    K = 6
    boxes = [make_box.remote() for _ in range(K)]
    got = ray.get(boxes[0])  # warm
    assert len(got) == 10000
    del got
    t0 = time.perf_counter()
    for box in boxes[1:]:
        got = ray.get(box)
        assert len(got) == 10000
        del got
    results["single_client_get_object_containing_10k_refs"] = (
        (K - 1) / (time.perf_counter() - t0))
    del boxes

    results.update(_client_bench())
    ray.shutdown()
    return results, raw_samples


_CLIENT_SCRIPT = r"""
import json, os, sys, time
import numpy as np
import ray_tpu as ray

ray.init(address=os.environ["RT_ADDR"], _authkey=os.environ["RT_KEY"])


@ray.remote
class CA:
    def m(self):
        return None


def timeit(fn, n, warm):
    fn(warm)
    t0 = time.perf_counter()
    fn(n)
    return n / (time.perf_counter() - t0)


out = {}
a = CA.remote()
ray.get(a.m.remote())
out["client_one_one_actor_calls_sync"] = timeit(
    lambda n: [ray.get(a.m.remote()) for _ in range(n)], 500, 50)
small = np.ones(1024, np.uint8)
out["client_put_calls"] = timeit(
    lambda n: [ray.put(small) for _ in range(n)], 1000, 100)
refs = [ray.put(small) for _ in range(500)]
t0 = time.perf_counter()
for r in refs:
    ray.get(r)
out["client_get_calls"] = 500 / (time.perf_counter() - t0)
big = np.ones(100 << 20, np.uint8)
gb = big.nbytes / 1e9
out["client_put_gigabytes"] = timeit(
    lambda n: [ray.put(big) for _ in range(n)], 8, 2) * gb
print("RESULT " + json.dumps(out))
"""


def _client_bench():
    """Ray-Client rows: a SUBPROCESS attaches in client mode and runs
    the reference's client__* loops (ray_perf.py client section)."""
    import os
    import subprocess
    import sys as _sys

    from ray_tpu._private import api_internal

    rt = api_internal.get_runtime()
    env = dict(os.environ,
               RT_ADDR=rt.tcp_address, RT_KEY=rt._authkey.hex(),
               JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    try:
        out = subprocess.run([_sys.executable, "-c", _CLIENT_SCRIPT],
                             capture_output=True, text=True, timeout=300,
                             env=env)
    except subprocess.TimeoutExpired:
        # A wedged client must not discard the core results already
        # collected.
        print("  client bench timed out; skipping client rows",
              file=sys.stderr)
        return {}
    for line in out.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    print(f"  client bench failed: {out.stderr[-500:]}", file=sys.stderr)
    return {}


def locality_bench():
    """Arg-locality microbench: a fan-out of tasks over one node-homed
    large arg, run with locality scheduling on and off — reports tasks/s
    and off_home_arg_bytes, the per-task upper bound on cross-node arg
    traffic (tasks that ran away from the arg's home node x arg size;
    singleflight dedup means actual wire bytes can be lower), so this
    PR's effect and regressions stay visible in the round trajectory."""
    import os

    import numpy as np

    import ray_tpu as ray
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy as NA,
    )

    arg_bytes = 8 << 20
    n_tasks = 64

    @ray.remote
    def make(n):
        return np.ones(n, np.uint8)

    @ray.remote
    def crunch(a):
        return os.environ["RAY_TPU_NODE_ID"]

    def run(system_config):
        c = Cluster(head_num_cpus=4, _system_config=system_config)
        try:
            home = c.add_node(num_cpus=4, external=True)
            ref = make.options(scheduling_strategy=NA(home)).remote(
                arg_bytes)
            ray.wait([ref], num_returns=1, timeout=60)
            ray.get([crunch.remote(ref) for _ in range(4)], timeout=120)
            t0 = time.perf_counter()
            nodes = ray.get([crunch.remote(ref) for _ in range(n_tasks)],
                            timeout=300)
            dt = time.perf_counter() - t0
            # Worker prefetch/dedup deltas arrive on the periodic
            # flusher: wait for the counters to settle before recording.
            stats = c.rt.transfer_stats()
            deadline = time.perf_counter() + 3.0
            while time.perf_counter() < deadline:
                time.sleep(0.3)
                nxt = c.rt.transfer_stats()
                if nxt == stats:
                    break
                stats = nxt
            return {
                "tasks_per_s": round(n_tasks / dt, 1),
                "off_home_arg_bytes":
                    sum(1 for nd in nodes if nd != home) * arg_bytes,
                "on_home_node": nodes.count(home),
                "locality_hits": stats["locality_hits"],
                "locality_misses": stats["locality_misses"],
                "locality_bytes_saved": stats["locality_bytes_saved"],
                "prefetch_hit_bytes": stats["prefetch_hit_bytes"],
                "deduped_pulls": stats["deduped_pulls"],
            }
        finally:
            c.shutdown()

    out = {"arg_mb": arg_bytes >> 20, "n_tasks": n_tasks,
           "locality_on": run(None),
           "locality_off": run({"locality_scheduling": False})}
    print(f"  [locality] on: {out['locality_on']['tasks_per_s']}/s, "
          f"{out['locality_on']['off_home_arg_bytes'] >> 20} MB off-home; "
          f"off: {out['locality_off']['tasks_per_s']}/s, "
          f"{out['locality_off']['off_home_arg_bytes'] >> 20} MB off-home",
          file=sys.stderr)
    return out


def data_streaming_bench():
    """ray_tpu.data streaming-engine row: a fixed 3-stage paced pipeline
    (fused chain, 2 MB output blocks) run with the operator-graph
    executor on vs the legacy windowed path — rows/s and the engine's
    peak in-flight bytes, so the backpressured engine's admission win
    (bytes-budgeted, cluster-wide — vs the legacy 8-chain count window)
    and any regression stay visible in the round trajectory.  Stages are
    paced with sleeps at num_cpus=0 so the comparison measures engine
    structure, not host load."""
    import numpy as np

    import ray_tpu as ray
    from ray_tpu import data as rd

    n_blocks, rows_per_block = 32, 64
    blk = 2 << 20

    def build():
        def inflate(b):
            time.sleep(0.04)
            return {"x": np.zeros(blk // 8, np.float64)}

        def scale(b):
            time.sleep(0.02)
            return {"x": b["x"] + 1.0}

        def mark(b):
            time.sleep(0.02)
            return {"x": -b["x"]}

        return (rd.from_items(list(range(n_blocks * rows_per_block)),
                              parallelism=n_blocks)
                .map_batches(inflate, num_cpus=0)
                .map_batches(scale, num_cpus=0)
                .map_batches(mark, num_cpus=0))

    def run(streaming):
        sc = None if streaming else {"streaming_executor": False}
        ray.init(num_cpus=16, _system_config=sc)
        def consume(ds):
            # Consumption path (iter_batches, zero-copy whole blocks):
            # this is where the legacy path's 8-chain window binds
            # (materialize() opens the legacy window fully and would
            # measure nothing).
            for _ in ds.iter_batches(batch_size=None):
                pass

        try:
            consume(build())        # warm the worker pool
            t0 = time.perf_counter()
            ds = build()
            consume(ds)
            dt = time.perf_counter() - t0
            s = ds._stats.streaming_summary()
            return {
                "rows_per_s": round(n_blocks * rows_per_block / dt, 1),
                "wall_s": round(dt, 3),
                "peak_inflight_bytes": s["peak_inflight_bytes"],
                "admitted_tasks": s["admitted_tasks"],
                "backpressure_stalls": s["backpressure_stalls"],
            }
        finally:
            ray.shutdown()

    out = {"n_blocks": n_blocks, "block_mb": blk >> 20,
           "streaming_on": run(True), "streaming_off": run(False)}
    print(f"  [data_streaming] on: {out['streaming_on']['rows_per_s']} "
          f"rows/s, peak "
          f"{out['streaming_on']['peak_inflight_bytes'] >> 20} MB "
          f"in-flight; off: {out['streaming_off']['rows_per_s']} rows/s",
          file=sys.stderr)
    return out


def serve_paged_bench():
    """Serving memory-plane rows (in-process, sleep-paced so the A/B
    measures engine structure): (a) skewed-length paged-vs-dense at
    EQUAL simulated HBM — dense gets hbm/max_seq_len slots, paged gets
    hbm/block_size blocks, so the ratio is pure block-granular packing;
    (b) prefix-cache variant — 12 clients sharing a 512-token system
    prompt, cached vs uncached, decoded chains bitwise-compared;
    (c) speculative decoding — draft k=4 vs greedy, exact-match
    acceptance, chains bitwise-compared.  Best-of-3 with raw samples."""
    import threading

    from ray_tpu.serve.continuous import _ContinuousBatcher
    from ray_tpu.serve.kv_cache import PagedKVEngine
    from ray_tpu.serve.tpu_replica import MeshShardedDecoder

    def drive(b, reqs, timeout=120):
        results, lats = {}, {}

        def client(i, r):
            t0 = time.perf_counter()
            results[i] = b.submit(r)
            lats[i] = time.perf_counter() - t0

        threads = [threading.Thread(target=client, args=(i, r))
                   for i, r in enumerate(reqs)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout)
        wall = time.perf_counter() - t0
        assert len(results) == len(reqs), "paged bench request failed"
        return results, lats, wall

    out = {}

    # -- (a) skewed-length paged-vs-dense at equal HBM ---------------------
    step_s, hbm_tokens, max_seq, bs = 0.004, 1024, 128, 8
    reqs = [{"tokens": max_seq if i % 16 == 0 else 16} for i in range(96)]

    def paced(slots):
        time.sleep(step_s)
        for s in slots:
            s.state = (s.state or 0) + 1
            if s.state >= s.request["tokens"]:
                s.finish(s.state)

    def ab_run(paged):
        best, samples = None, []
        for _ in range(3):
            kv = PagedKVEngine(
                hbm_tokens // bs, bs, prefix_caching=False, max_slots=64,
                tokens_for=lambda r: ((), r["tokens"])) if paged else None
            b = _ContinuousBatcher(paced, None, hbm_tokens // max_seq,
                                   0.0, continuous=True, kv=kv)
            _, lats, wall = drive(b, reqs)
            flat = sorted(lats.values())
            row = {
                "req_s": round(len(reqs) / wall, 1),
                "p50_ms": round(flat[len(flat) // 2] * 1e3, 2),
                "p99_ms": round(flat[min(len(flat) - 1,
                                         int(len(flat) * 0.99))] * 1e3,
                                2),
                "batch_occupancy": b.stats()["batch_occupancy"],
            }
            samples.append(row)
            if best is None or row["req_s"] > best["req_s"]:
                best = row
        return {**best, "samples": samples}

    dense, paged = ab_run(False), ab_run(True)
    out["paged_ab"] = {
        "hbm_tokens": hbm_tokens, "max_seq_len": max_seq,
        "block_size": bs, "dense": dense, "paged": paged,
        "speedup_req_s": round(paged["req_s"] / max(dense["req_s"],
                                                    1e-9), 2),
    }
    print(f"  [serve-paged] A/B at {hbm_tokens}-token HBM: paged "
          f"{paged['req_s']} req/s (occ {paged['batch_occupancy']}) vs "
          f"dense {dense['req_s']} req/s (occ "
          f"{dense['batch_occupancy']}) — "
          f"{out['paged_ab']['speedup_req_s']}x", file=sys.stderr)

    # -- (b) prefix-cache variant: shared 512-token system prompt ----------
    sys_prompt = [i % 64 for i in range(512)]
    preqs = [{"prompt": sys_prompt + [i], "tokens": 4 + i % 5}
             for i in range(12)]

    def decode_run(prefix_on):
        best, samples, outs = None, [], None
        for _ in range(3):
            dec = MeshShardedDecoder(paged=True, kv_blocks=128,
                                     kv_block_size=16, max_slots=16,
                                     prefix_caching=prefix_on,
                                     speculative_k=0)
            b = _ContinuousBatcher(dec._paged_step, None, 8, 0.0,
                                   continuous=True, kv=dec.serve_kv_engine)
            results, _, wall = drive(b, preqs)
            s = b.stats()
            row = {"req_s": round(len(preqs) / wall, 1),
                   "prefix_hits": s["prefix_hits"],
                   "prefix_blocks_shared": s["prefix_blocks_shared"],
                   "cow_copies": s["cow_copies"],
                   "admission_parks": s["admission_parks"]}
            samples.append(row)
            if best is None or row["req_s"] > best["req_s"]:
                best = row
            outs = results  # identical across rounds (greedy, pinned)
        return {**best, "samples": samples}, outs

    cached, cached_outs = decode_run(True)
    uncached, uncached_outs = decode_run(False)
    ref = MeshShardedDecoder()
    out["prefix_cache"] = {
        "prompt_tokens": len(sys_prompt), "clients": len(preqs),
        "cached": cached, "uncached": uncached,
        "bitwise_identical": cached_outs == uncached_outs == {
            i: ref.reference_decode(r["prompt"], r["tokens"])
            for i, r in enumerate(preqs)},
        "speedup_req_s": round(cached["req_s"]
                               / max(uncached["req_s"], 1e-9), 2),
    }
    print(f"  [serve-paged] prefix cache (512-token shared prompt): "
          f"{cached['req_s']} req/s, {cached['prefix_hits']} hits, "
          f"{cached['prefix_blocks_shared']} blocks shared vs uncached "
          f"{uncached['req_s']} req/s "
          f"({out['prefix_cache']['speedup_req_s']}x, bitwise="
          f"{out['prefix_cache']['bitwise_identical']})", file=sys.stderr)

    # -- (c) speculative decoding ------------------------------------------
    sreqs = [{"prompt": [i], "tokens": 8 + i % 8} for i in range(12)]

    def spec_run(k):
        dec = MeshShardedDecoder(paged=True, kv_blocks=64,
                                 kv_block_size=8, speculative_k=k)
        b = _ContinuousBatcher(dec._paged_step, None, 8, 0.0,
                               continuous=True, kv=dec.serve_kv_engine)
        results, _, wall = drive(b, sreqs)
        s = b.stats()
        return results, {"req_s": round(len(sreqs) / wall, 1),
                         "steps": s["steps"],
                         "tokens_per_step": s["tokens_per_step"],
                         "spec_proposed": s["spec_proposed"],
                         "spec_accepted": s["spec_accepted"]}

    greedy_outs, greedy = spec_run(0)
    spec_outs, spec = spec_run(4)
    out["speculative"] = {
        "k": 4, "greedy": greedy, "spec": spec,
        "accept_rate": round(spec["spec_accepted"]
                             / max(spec["spec_proposed"], 1), 3),
        "bitwise_identical": spec_outs == greedy_outs == {
            i: ref.reference_decode(r["prompt"], r["tokens"])
            for i, r in enumerate(sreqs)},
    }
    print(f"  [serve-paged] speculative k=4: "
          f"{spec['tokens_per_step']} tokens/step "
          f"(greedy {greedy['tokens_per_step']}), accept rate "
          f"{out['speculative']['accept_rate']}, bitwise="
          f"{out['speculative']['bitwise_identical']}", file=sys.stderr)
    return out


def serve_latency_bench():
    """Serving hot-path row: p50/p99 latency and req/s under N
    concurrent clients driving a paced continuous-batching decode
    deployment THROUGH the RequestProxy tier (client actor → proxy →
    replica step loop), continuous batching on vs off at equal
    max_batch_size — best-of-3 with the raw per-round samples kept in
    the round JSON, plus the steady-state head_brokered_submits delta
    (the proxy-tier observable: 0 — request traffic rides the direct
    actor channels).  Steps are sleep-paced so the A/B measures engine
    structure, not host load."""
    import ray_tpu as ray
    from ray_tpu import serve

    n_clients, reqs_per_client = 8, 12
    step_s = 0.004

    def run(continuous):
        sc = None if continuous else {"continuous_batching": False}
        rt = ray.init(num_cpus=16, _system_config=sc)
        try:
            @serve.deployment(num_replicas=1, max_concurrency=32)
            class Decode:
                @serve.batch(mode="continuous", max_batch_size=8,
                             batch_wait_timeout_s=0.05)
                def step(self, slots):
                    time.sleep(step_s)
                    for s in slots:
                        if s.state is None:
                            s.state = {"n": 0,
                                       "need": s.request["tokens"]}
                        s.state["n"] += 1
                        if s.state["n"] >= s.state["need"]:
                            s.finish(s.state["n"])

                def __call__(self, body):
                    return self.step(body)

            serve.start(proxy_location="Disabled", num_proxies=2)
            serve.run(Decode.bind(), name="decode")
            proxies = serve.api._state["request_proxies"]

            @ray.remote
            class Client:
                def run(self, proxies, n, depth=4):
                    """Pipelined client: up to `depth` requests in
                    flight (a sequential client's think-time RTT would
                    idle freed batch slots and measure the wire, not
                    the engine)."""
                    import time as _t

                    import ray_tpu as ray
                    lats = []
                    inflight = {}  # ref -> submit time
                    i = 0
                    while i < n or inflight:
                        while i < n and len(inflight) < depth:
                            body = {"tokens": 24 if i % 4 == 0 else 2}
                            ref = proxies[i % len(proxies)] \
                                .handle_request.remote(
                                    "decode", (body,), None)
                            inflight[ref] = _t.perf_counter()
                            i += 1
                        done, _ = ray.wait(list(inflight),
                                           num_returns=1, timeout=120)
                        for r in done:
                            lats.append(
                                _t.perf_counter() - inflight.pop(r))
                            ray.get(r)
                    return lats

            clients = [Client.remote() for _ in range(n_clients)]
            ray.get([c.run.remote(proxies, 2) for c in clients],
                    timeout=300)  # warm actor channels + batcher
            time.sleep(1.0)
            before = rt.transfer_stats()["head_brokered_submits"]
            best = None
            samples = []
            for _ in range(3):
                t0 = time.perf_counter()
                lats = ray.get(
                    [c.run.remote(proxies, reqs_per_client)
                     for c in clients], timeout=600)
                dt = time.perf_counter() - t0
                flat = sorted(x for ls in lats for x in ls)
                total = n_clients * reqs_per_client
                row = {
                    "req_s": round(total / dt, 1),
                    "p50_ms": round(flat[len(flat) // 2] * 1e3, 2),
                    "p99_ms": round(
                        flat[min(len(flat) - 1,
                                 int(len(flat) * 0.99))] * 1e3, 2),
                }
                samples.append(row)
                if best is None or row["req_s"] > best["req_s"]:
                    best = row
            delta = rt.transfer_stats()["head_brokered_submits"] - before
            stats = serve.serving_stats("decode")
            return {**best, "samples": samples,
                    "head_brokered_delta": delta,
                    "batch_occupancy": stats.get("batch_occupancy"),
                    "steps": stats.get("steps"),
                    "mode": stats.get("mode")}
        finally:
            serve.shutdown()
            ray.shutdown()

    out = {"n_clients": n_clients, "reqs_per_client": reqs_per_client,
           "step_ms": step_s * 1e3,
           "continuous_on": run(True), "continuous_off": run(False)}
    on, off = out["continuous_on"], out["continuous_off"]
    out["speedup_req_s"] = round(on["req_s"] / max(off["req_s"], 1e-9), 2)
    print(f"  [serve] continuous: {on['req_s']} req/s, p50 "
          f"{on['p50_ms']}ms, p99 {on['p99_ms']}ms; one-shot: "
          f"{off['req_s']} req/s ({out['speedup_req_s']}x); "
          f"head_brokered_delta={on['head_brokered_delta']}",
          file=sys.stderr)
    # Serving memory plane (paged KV / prefix cache / speculative): its
    # failure must not discard the base serve row.
    try:
        out["paged"] = serve_paged_bench()
    except Exception as e:  # noqa: BLE001 — sub-row must not kill the row
        print(f"  [serve-paged] bench failed: {e!r}", file=sys.stderr)
        out["paged"] = {"error": repr(e)}
    return out


def disagg_serving_bench():
    """Disaggregated prefill/decode row: p50 time-to-first-token and
    req/s under mixed traffic — long-prompt "doc" requests (112-token
    prompts drawn from 15 prefix families) interleaved with
    short-decode "chat" requests — disaggregated (3 prefill + 2
    decode replicas, KV chains streamed over the striped put path) vs
    the monolithic engine (5 identical replicas) at equal replica
    count, best-of-3 with raw per-round samples.  The mechanism under
    test is cache partitioning: 15 families x 14 blocks each cannot
    fit in ONE 96-block replica pool (~6.9 families), so monolithic
    p2c — which spreads every family across all five replicas — holds
    a sub-half hit rate STRUCTURALLY and pays the full 896 ms
    re-prefill on most docs, while prefix-affinity routing pins 5
    families to each prefill home (70 of 96 blocks) where they all
    fit and steady-state doc prefills are tail-only (the request
    tails are unique per round, so rounds measure the shared-prefix
    mechanism, not whole-prompt replay).  A third leg re-runs
    disaggregated mode with prefix_affinity off (pure p2c = the
    random-routing baseline) and compares the summed engine
    prefix-cache hits.  Prefill pacing (8 ms/token synthetic stall,
    one sleep per engine step) makes prefill cost dominate the
    millisecond-scale host noise, as in the other serve rows."""
    import ray_tpu as ray
    from ray_tpu import serve

    prefill_ms = 8.0
    doc_len, doc_tail, doc_tokens = 96, 16, 2
    chat_pre, chat_tail, chat_tokens = 32, 4, 8
    kv_blocks, kv_block = 96, 8
    doc_gap_s, chat_gap_s = 0.17, 0.21
    n_docs, n_chats = 30, 24
    n_chat_families, n_doc_families = 2, 15

    def doc_prompt(i):
        fam = i % n_doc_families
        return ([(7 + fam * 5 + j) % 64 for j in range(doc_len)]
                + [(i * 13 + j) % 64 for j in range(doc_tail)])

    def chat_prompt(i):
        fam = i % n_chat_families
        return ([(31 + fam * 11 + j) % 64 for j in range(chat_pre)]
                + [(i * 17 + j) % 64 for j in range(chat_tail)])

    def run(disagg, affinity):
        from ray_tpu.serve.tpu_replica import MeshShardedDecoder

        sc = {"paged_kv": True, "disaggregated_serving": disagg,
              "prefix_affinity": affinity}
        rt = ray.init(num_cpus=16, _system_config=sc)
        try:
            dep = serve.deployment(
                MeshShardedDecoder, name="mix", max_concurrency=48,
                num_replicas=(2 if disagg else 5),
                prefill_replicas=(3 if disagg else 0))
            handle = serve.run(
                dep.bind(kv_blocks=kv_blocks, kv_block_size=kv_block,
                         max_slots=16, use_kernel=False,
                         speculative_k=3,
                         prefill_ms_per_token=prefill_ms),
                name="mix")
            # The twin's replicas spawn asynchronously; pinning a
            # family while a pool is below strength parks every home
            # on one replica, so wait for full strength first.
            deadline = time.perf_counter() + 30
            while time.perf_counter() < deadline:
                with handle._lock:
                    n_dec = len(handle._replicas)
                    n_pre = len(handle._prefill_replicas)
                if n_dec >= (2 if disagg else 5) and \
                        (not disagg or n_pre >= 3):
                    break
                time.sleep(0.05)
            # Warmup pins each doc family to a prefill home (p2c
            # steers successive long prefills apart), then a parallel
            # pass warms the compile caches on the pinned paths.
            for f in range(n_doc_families):
                ray.get(handle.remote({"prompt": doc_prompt(f),
                                       "tokens": doc_tokens}),
                        timeout=120)
            for f in range(n_chat_families):
                ray.get(handle.remote({"prompt": chat_prompt(f),
                                       "tokens": chat_tokens}),
                        timeout=120)
            warm = [handle.remote(
                {"prompt": doc_prompt(n_doc_families + f),
                 "tokens": doc_tokens}) for f in range(n_doc_families)]
            warm += [handle.remote(
                {"prompt": chat_prompt(n_chat_families + f),
                 "tokens": chat_tokens})
                for f in range(2 * n_chat_families)]
            ray.get(warm, timeout=120)

            def one_round(r):
                events = []
                for i in range(n_docs):
                    events.append((i * doc_gap_s, {
                        "prompt": doc_prompt(100 + r * n_docs + i),
                        "tokens": doc_tokens}, False))
                for i in range(n_chats):
                    events.append((i * chat_gap_s, {
                        "prompt": chat_prompt(100 + r * n_chats + i),
                        "tokens": chat_tokens}, True))
                events.sort(key=lambda e: e[0])
                before = rt.transfer_stats()["head_brokered_submits"]
                inflight = {}
                ttfts = {"doc": [], "chat": []}
                t0 = time.perf_counter()
                k = 0
                # Open-loop driver: requests go out on the offered
                # schedule whether or not the engine keeps up, so a
                # saturated engine shows queue growth in TTFT instead
                # of silently shedding load.
                while k < len(events) or inflight:
                    now = time.perf_counter() - t0
                    while k < len(events) and events[k][0] <= now:
                        _, body, chat = events[k]
                        k += 1
                        body = dict(body)
                        body["_timing"] = True
                        body["_t0"] = time.time()
                        inflight[handle.remote(body)] = chat
                    if not inflight:
                        time.sleep(0.001)
                        continue
                    done, _ = ray.wait(list(inflight), num_returns=1,
                                       timeout=0.002)
                    for r in done:
                        chat = inflight.pop(r)
                        out = ray.get(r)
                        ttfts["chat" if chat else "doc"].append(
                            out["ttft"])
                wall = time.perf_counter() - t0
                delta = rt.transfer_stats()["head_brokered_submits"] \
                    - before

                def pct(vals, q):
                    vals = sorted(vals)
                    return round(
                        vals[min(len(vals) - 1,
                                 int(len(vals) * q))] * 1e3, 2)

                both = ttfts["doc"] + ttfts["chat"]
                return {
                    "p50_ttft_ms": pct(both, 0.5),
                    "p90_ttft_ms": pct(both, 0.9),
                    "doc_p50_ttft_ms": pct(ttfts["doc"], 0.5),
                    "chat_p50_ttft_ms": pct(ttfts["chat"], 0.5),
                    "req_s": round((n_docs + n_chats) / wall, 1),
                    "wall_s": round(wall, 2),
                    "head_brokered_delta": delta,
                }

            samples = [one_round(r) for r in range(3)]
            best = min(samples, key=lambda s: s["p50_ttft_ms"])
            stats = serve.serving_stats("mix")
            return {**best, "samples": samples,
                    "prefix_hits": stats.get("prefix_hits"),
                    "kv_chains_exported": stats.get(
                        "kv_chains_exported"),
                    "kv_chain_bytes_streamed": stats.get(
                        "kv_chain_bytes_streamed"),
                    "router": handle.router_stats()}
        finally:
            serve.shutdown()
            ray.shutdown()

    out = {
        "workload": {
            "prefill_ms_per_token": prefill_ms,
            "doc_prompt_len": doc_len + doc_tail,
            "chat_prompt_len": chat_pre + chat_tail,
            "doc_families": n_doc_families,
            "offered_req_s": round(
                1.0 / doc_gap_s + 1.0 / chat_gap_s, 1),
        },
        "disagg": run(True, True),
        "mono": run(False, True),
        "random_routing": run(True, False),
    }
    d, m, r = out["disagg"], out["mono"], out["random_routing"]
    out["ttft_p50_speedup"] = round(
        m["p50_ttft_ms"] / max(d["p50_ttft_ms"], 1e-9), 2)
    out["req_s_ratio"] = round(d["req_s"] / max(m["req_s"], 1e-9), 2)
    out["affinity_vs_random_prefix_hits"] = {
        "affinity": d["prefix_hits"], "random": r["prefix_hits"]}
    print(f"  [disagg_serving] disagg: p50 ttft {d['p50_ttft_ms']}ms, "
          f"{d['req_s']} req/s; mono: {m['p50_ttft_ms']}ms, "
          f"{m['req_s']} req/s ({out['ttft_p50_speedup']}x ttft, "
          f"{out['req_s_ratio']}x req/s); prefix_hits affinity="
          f"{d['prefix_hits']} random={r['prefix_hits']}; "
          f"chain_bytes={d['kv_chain_bytes_streamed']}, "
          f"head_brokered_delta={d['head_brokered_delta']}",
          file=sys.stderr)
    return out


def recovery_bench():
    """Fault-tolerance row: a 32-task fan-out (2 MB results pinned to an
    external node) suffers a mid-run worker kill (tasks retry) and then
    loses the node itself before the results are consumed — recovery on
    vs off.  Reports completion wall-clock, whether every get returned
    the correct value, and the reconstruction counter; best-of-3 per
    mode with raw samples in the round JSON (PR 6-8 convention).  The
    off run documents today's failure (ObjectLostError at get), so the
    row keeps both the subsystem's cost and its value in the
    trajectory."""
    import numpy as np

    import ray_tpu as ray
    from ray_tpu.chaos import ChaosController
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy as NA,
    )

    n_tasks = 32

    @ray.remote(max_retries=3)
    def make(i):
        time.sleep(0.02)
        return np.full(260_000, i, dtype=np.int64)

    @ray.remote
    def check(a):
        return int(a[0])

    def one_round(system_config):
        c = Cluster(head_num_cpus=4, _system_config=system_config)
        chaos = None
        try:
            node = c.add_node(num_cpus=4, external=True)
            chaos = ChaosController(c.rt)
            t0 = time.perf_counter()
            s1 = [make.options(scheduling_strategy=NA(
                node_id=node, soft=True)).remote(i)
                for i in range(n_tasks)]
            time.sleep(0.15)
            chaos.kill_worker(mid_task=True)  # retries absorb this
            ray.wait(s1, num_returns=len(s1), timeout=120)
            chaos.kill_agent(node)  # results lost before consumption
            ok = True
            try:
                vals = ray.get([check.remote(r) for r in s1],
                               timeout=120)
                ok = vals == list(range(n_tasks))
            except ray.exceptions.RayTpuError:
                ok = False
            dt = time.perf_counter() - t0
            stats = c.rt.transfer_stats()
            return {"wall_s": round(dt, 2), "completed": ok,
                    "reconstructions": stats["reconstructions"],
                    "chaos_kills": stats["chaos_kills"]}
        finally:
            if chaos is not None:
                chaos.stop()
            c.shutdown()

    def best_of(system_config, rounds=3):
        samples = [one_round(system_config) for _ in range(rounds)]
        best = min(samples, key=lambda s: (not s["completed"],
                                           s["wall_s"]))
        return {**best, "samples": samples}

    out = {"n_tasks": n_tasks,
           "recovery_on": best_of(None),
           "recovery_off": best_of({"recovery": False})}
    on, off = out["recovery_on"], out["recovery_off"]
    print(f"  [recovery] on: {on['wall_s']}s, completed={on['completed']},"
          f" reconstructions={on['reconstructions']}; off: "
          f"{off['wall_s']}s, completed={off['completed']}",
          file=sys.stderr)
    return out


def degraded_link_bench():
    """Failure-detection row: a 4-node pull fan-out (producers homed on
    one node, consumers spread over the other three pulling ~2 MB args
    across the wire) with the producer node's DATA LINK stalled
    mid-transfer (env net-chaos rule: its object server parks at chunk
    2, socket open — the gray failure, nothing EOFs).
    ``failure_detection`` on vs off: on, every pull's zero-progress
    deadline trips, the transport retries, then hedges to the
    head-relay fallback — completion bounded in seconds with the
    stall/retry/hedge counters lit; off, the pulls block forever and
    the run only ends at the get timeout (reported timeout-bounded —
    today's behavior, the row documents exactly what the plane buys).
    Best-of-3 per mode with raw samples (PR 6/7 convention)."""
    import tempfile

    import numpy as np

    import ray_tpu as ray
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy as NA,
    )

    n_objects = 9
    get_timeout_s = 10.0

    @ray.remote(max_retries=3)
    def make(i):
        return np.full(260_000, i, dtype=np.int64)  # ~2 MB

    @ray.remote(max_retries=3)
    def consume(a):
        return int(a[0])

    def one_round(fd_on):
        cfg = {"failure_detection": fd_on}
        if fd_on:
            cfg.update({"net_stall_timeout_s": 0.5, "net_retry_count": 1,
                        "net_retry_backoff_base_ms": 20.0})
        chaos_dir = tempfile.mkdtemp()
        # The head merges its own process-wide deadline-core counters
        # into transfer_stats; rounds share this driver process, so
        # report per-round DELTAS (the off round must read zero).
        from ray_tpu._private import protocol as _protocol

        base = _protocol.net_stats()
        c = Cluster(head_num_cpus=0, _system_config=cfg)
        try:
            src = c.add_node(
                num_cpus=2, external=True,
                env_overrides={
                    "RAY_TPU_CHAOS_NET": "agent:chunk_send:stall:2",
                    "RAY_TPU_CHAOS_DIR": chaos_dir,
                })
            sinks = [c.add_node(num_cpus=1, external=True)
                     for _ in range(3)]
            s1 = [make.options(scheduling_strategy=NA(
                node_id=src, soft=True)).remote(i)
                for i in range(n_objects)]
            ray.wait(s1, num_returns=len(s1), timeout=60)
            t0 = time.perf_counter()
            s2 = [consume.options(scheduling_strategy=NA(
                node_id=sinks[i % 3], soft=True)).remote(r)
                for i, r in enumerate(s1)]
            ok = True
            try:
                vals = ray.get(s2, timeout=get_timeout_s)
                ok = vals == list(range(n_objects))
            except ray.exceptions.RayTpuError:
                ok = False  # off: the gray stall only ends at timeout
            dt = time.perf_counter() - t0
            stats = c.rt.transfer_stats()
            return {"wall_s": round(dt, 2), "completed": ok,
                    "timeout_bounded": not ok,
                    "stall_timeouts":
                        stats["stall_timeouts"] - base["stall_timeouts"],
                    "net_retries":
                        stats["net_retries"] - base["net_retries"],
                    "hedged_fetches":
                        stats["hedged_fetches"] - base["hedged_fetches"],
                    "suspected_nodes": stats["suspected_nodes"]}
        finally:
            c.shutdown()

    def best_of(fd_on, rounds=3):
        samples = [one_round(fd_on) for _ in range(rounds)]
        best = min(samples, key=lambda s: (not s["completed"],
                                           s["wall_s"]))
        return {**best, "samples": samples}

    out = {"n_objects": n_objects, "get_timeout_s": get_timeout_s,
           "failure_detection_on": best_of(True),
           "failure_detection_off": best_of(False)}
    on, off = out["failure_detection_on"], out["failure_detection_off"]
    print(f"  [degraded_link] on: {on['wall_s']}s, completed="
          f"{on['completed']}, stalls={on['stall_timeouts']}, retries="
          f"{on['net_retries']}, hedged={on['hedged_fetches']}; off: "
          f"{off['wall_s']}s, completed={off['completed']} "
          f"(timeout-bounded={off['timeout_bounded']})",
          file=sys.stderr)
    return out


def shuffle_bench(rounds=3):
    """Push-shuffle row: an all-to-all sort + groupby with the PULL-
    SERVE PLANE paced (env net-chaos ``delay`` on every agent
    data-chunk send, one claim dir per node so every node's object
    server is paced, ``object_pool_size=1`` so transfers per peer pair
    serialize like a real bandwidth-limited link), push engine on vs
    off on identical data.  The paced resource is the per-node serve
    path that the legacy engine routes EVERY partition byte through at
    the reduce barrier; the push engine's whole thesis is that map-side
    ``put_range`` writes partition bytes straight into the consumer
    store and never queues behind that plane (its input-block reads
    still pay the same paced pulls, so the comparison shares the slow
    plane for everything except the contested partition hop).  Pacing
    also makes the A/B load-independent on a 2-vCPU host: walls are
    dominated by deterministic injected sleeps, not scheduler noise.
    ``max_inline_object_size`` is lowered so the legacy engine's
    partitions (~320 KB at R=16) are node-store homed and actually
    traverse the data plane rather than riding head messages.

    ``gbps`` = dataset bytes / wall to full consumption; ``completed``
    pins exact row counts.  Both modes must keep the head control
    plane flat — ``head_brokered_submits`` and ``brokered_put_parts``
    per-run DELTAS zero (no partition payload or spec ever rides a
    head message).  Best-of-``rounds`` per mode with raw samples
    (PR 6/7 convention), plus a chaos variant: kill one producer node
    AND gray-stall another's head link mid-shuffle — lineage rebuild +
    reducer hedging must still land the exact sorted output."""
    import pickle
    import tempfile

    import numpy as np

    import ray_tpu as ray
    from ray_tpu import data as rd
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.data.dataset import Dataset
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy as NA,
    )

    n_blocks = 16
    rows_per_block = 600
    n_groups = 7
    delay_ms = 240
    part_target = 20_000_000  # R=4 on ~80 MB: push partitions ~1.25 MB,
    # R decoupled from the 16-block count (legacy is locked to R=16).

    def _mk_rows(i):
        rng = np.random.default_rng(77 + i)
        return [{"k": float(v), "g": j % n_groups, "v": j,
                 "p": bytes(8192)}
                for j, v in enumerate(rng.random(rows_per_block))]

    @ray.remote(max_retries=3)
    def mk_block(i):
        return _mk_rows(i)

    block_bytes = len(pickle.dumps(_mk_rows(0), protocol=5))
    total_bytes = block_bytes * n_blocks
    total_rows = rows_per_block * n_blocks

    pace = f"agent:chunk_send:delay-{delay_ms}:1"

    def one_round(push_on):
        cfg = {"push_shuffle": push_on,
               "shuffle_partition_bytes_target": part_target,
               "max_inline_object_size": 65536,
               "object_pool_size": 1}
        c = Cluster(head_num_cpus=0, _system_config=cfg)
        try:
            nodes = [c.add_node(
                num_cpus=2, external=True,
                env_overrides={
                    "RAY_TPU_CHAOS_NET": pace,
                    # A claim dir PER NODE: the one-shot claim-file
                    # convention then arms the delay once per node —
                    # every node's serve plane paced.
                    "RAY_TPU_CHAOS_DIR": tempfile.mkdtemp(),
                }) for _ in range(2)]
            blocks = [mk_block.options(scheduling_strategy=NA(
                node_id=nodes[i % 2], soft=True)).remote(i)
                for i in range(n_blocks)]
            ray.wait(blocks, num_returns=len(blocks), timeout=60)

            def timed(build, expect_rows):
                st0 = c.rt.transfer_stats()
                t0 = time.perf_counter()
                n = build(Dataset(blocks)).count()
                dt = time.perf_counter() - t0
                st1 = c.rt.transfer_stats()

                def delta(k):
                    return st1.get(k, 0) - st0.get(k, 0)

                return {"wall_s": round(dt, 2),
                        "gbps": round(total_bytes / 1e9 / dt, 4),
                        "completed": n == expect_rows,
                        "head_brokered_submits":
                            delta("head_brokered_submits"),
                        "brokered_put_parts": delta("brokered_put_parts"),
                        "shuffle_pushed_bytes":
                            delta("shuffle_pushed_bytes"),
                        "shuffle_hedges": delta("shuffle_hedges")}

            sort_row = timed(lambda ds: ds.sort(key="k"), total_rows)
            grp_row = timed(
                lambda ds: ds.groupby("g").aggregate(
                    rd.Sum("v"), rd.Count()), n_groups)
            return sort_row, grp_row
        finally:
            c.shutdown()

    def best_of(push_on):
        pairs = [one_round(push_on) for _ in range(rounds)]

        def pick(samples):
            best = min(samples,
                       key=lambda s: (not s["completed"], -s["gbps"]))
            return {**best, "samples": samples}

        return (pick([p[0] for p in pairs]),
                pick([p[1] for p in pairs]))

    def chaos_round():
        """The drill as a bench row: unpaced 3-node cluster, input
        blocks homed on the doomed nodes, kill + gray-stall the moment
        the map wave is submitted."""
        from ray_tpu.chaos import ChaosController

        fd = {"net_stall_timeout_s": 0.8, "net_connect_timeout_s": 2.0,
              "net_retry_count": 1, "net_retry_backoff_base_ms": 20.0,
              "health_check_period_s": 0.25,
              "health_check_timeout_s": 1.0,
              "health_check_failure_threshold": 2,
              "health_check_initial_delay_s": 1.0}
        c = Cluster(head_num_cpus=2, _system_config=fd)
        chaos = None
        try:
            n1 = c.add_node(num_cpus=2, external=True)
            n2 = c.add_node(num_cpus=2, external=True)
            n3 = c.add_node(num_cpus=2, external=True)
            chaos = ChaosController(c.rt)
            homes = [n1, n2, n1, n3]
            blocks = [mk_block.options(scheduling_strategy=NA(
                node_id=homes[i % len(homes)], soft=True)).remote(i)
                for i in range(n_blocks)]
            ray.wait(blocks, num_returns=len(blocks), timeout=60)

            def wreck():
                chaos.kill_agent(n1)
                chaos.stall_link(n2)

            chaos.at_syncpoint("shuffle:maps_submitted", wreck, n=1)
            t0 = time.perf_counter()
            n = Dataset(blocks).sort(key="k").count()
            dt = time.perf_counter() - t0
            st = c.rt.transfer_stats()
            return {"wall_s": round(dt, 2), "completed": n == total_rows,
                    "reconstructions": st.get("reconstructions", 0),
                    "shuffle_hedges": st.get("shuffle_hedges", 0)}
        finally:
            if chaos is not None:
                chaos.stop()
            c.shutdown()

    sort_push, grp_push = best_of(True)
    sort_legacy, grp_legacy = best_of(False)
    try:
        chaos_row = chaos_round()
    except Exception as e:  # noqa: BLE001 — extra row must not kill A/B
        chaos_row = {"error": repr(e)}

    out = {"dataset_mb": round(total_bytes / 1e6, 2),
           "delay_ms": delay_ms, "rounds": rounds,
           "sort_push": sort_push, "sort_legacy": sort_legacy,
           "groupby_push": grp_push, "groupby_legacy": grp_legacy,
           "chaos": chaos_row}
    sp, sl = out["sort_push"], out["sort_legacy"]
    print(f"  [shuffle] sort push {sp['gbps']}GB/s vs legacy "
          f"{sl['gbps']}GB/s ({sp['gbps'] / max(sl['gbps'], 1e-9):.2f}x),"
          f" groupby {grp_push['gbps']}GB/s vs {grp_legacy['gbps']}GB/s;"
          f" chaos completed={chaos_row.get('completed')} "
          f"(reconstructions={chaos_row.get('reconstructions')}, "
          f"hedges={chaos_row.get('shuffle_hedges')})",
          file=sys.stderr)
    return out


def pipeline_train_bench(rounds=3):
    """Distributed pipeline-training row: a 2-stage llama-tiny actor
    pipeline on two paced external nodes (same env net-chaos pacing as
    the shuffle row: every data-plane chunk send — activation/grad
    stripe pushes included — pays a deterministic delay, so the A/B is
    load-independent and transfer cost is really on the wire).  The SAME
    trainer steps under both schedules, so weights, jit caches, and the
    paced link are identical: ``fill_drain`` drives synchronous per-
    stage wave barriers (the GPipe shape with every transfer on the
    critical path), ``1f1b`` the async one-forward-one-backward
    submission that overlaps microbatch t+1's transfer with t's compute
    across stages.  M = 2*pp microbatches (the 1F1B steady-state
    sweet spot).

    ``tok_s`` = batch tokens * steps / wall; ``bubble_fraction`` =
    1 - sum(stage busy_s deltas) / (pp * wall) — the measured idle
    share the schedule leaves on the stages.  Best-of-``rounds`` with
    raw samples (PR 6/7 convention), plus a chaos variant: SIGKILL a
    mid-pipeline stage mid-epoch — the epoch must complete from the
    stage's ``__ray_save__`` checkpoint with bounded replay
    (``stage_restarts`` >= 1) and zero ObjectLostError at the driver."""
    import tempfile

    import numpy as np

    import ray_tpu as ray
    from ray_tpu.cluster_utils import Cluster

    pp = 3
    M = 2 * pp
    batch, seq = 12, 16
    steps = 2
    delay_ms = 120
    # role "worker": the activation/grad stripe pushes run in the STAGE
    # ACTOR's process (`_send_piece_range`), not the node agent's serve
    # loop — pacing the agent (the shuffle row's choice) would leave
    # the push path free.
    pace = f"worker:chunk_send:delay-{delay_ms}:1"

    def build_trainer():
        import jax
        import optax

        from ray_tpu.models import llama as L
        from ray_tpu.train.pipeline_actors import PipelineTrainer

        cfg = L.LlamaConfig.tiny(num_layers=pp)  # one layer per stage
        params = L.init_params(jax.random.PRNGKey(0), cfg)
        tr = PipelineTrainer(
            L.make_pipeline_stage_fn(cfg), L.make_pipeline_loss_fn(cfg),
            L.pipeline_stage_params(params, pp),
            optimizer=optax.sgd(1e-2), num_microbatches=M,
            distributed=True)
        rng = np.random.default_rng(0)
        tok = rng.integers(0, cfg.vocab_size,
                           size=(batch, seq + 1)).astype(np.int32)
        return tr, tok[:, :-1], tok[:, 1:]

    def one_round():
        c = Cluster(head_num_cpus=0, _system_config={})
        try:
            # One CPU per node: the two stage actors are forced onto
            # DIFFERENT nodes, so every activation/grad hop crosses the
            # paced link.
            for _ in range(pp):
                c.add_node(num_cpus=1, external=True, env_overrides={
                    "RAY_TPU_CHAOS_NET": pace,
                    "RAY_TPU_CHAOS_DIR": tempfile.mkdtemp(),
                })
            tr, x, t = build_trainer()
            assert tr.distributed
            tr.step(x, t)  # warm the per-stage jit caches

            def timed(schedule):
                busy0 = sum(s["busy_s"] for s in tr.stage_stats())
                st0 = c.rt.transfer_stats()
                t0 = time.perf_counter()
                for _ in range(steps):
                    tr.step(x, t, schedule=schedule)
                dt = time.perf_counter() - t0
                busy1 = sum(s["busy_s"] for s in tr.stage_stats())
                st1 = c.rt.transfer_stats()
                time.sleep(1.2)  # the pushes counter flushes async
                st1 = c.rt.transfer_stats()
                return {
                    "wall_s": round(dt, 2),
                    "tok_s": round(batch * seq * steps / dt, 1),
                    "bubble_fraction": round(
                        1.0 - (busy1 - busy0) / (pp * dt), 3),
                    "microbatch_pushes": st1["microbatch_pushes"]
                    - st0["microbatch_pushes"],
                }

            fd = timed("fill_drain")
            ofb = timed("1f1b")
            tr.shutdown()
            return fd, ofb
        finally:
            c.shutdown()

    def chaos_round():
        """Mid-epoch SIGKILL of the last (loss) stage while a step's
        schedule is in flight; unpaced so the row stays quick."""
        import threading

        rt = ray.init(num_cpus=4, num_tpus=0)
        try:
            tr, x, t = build_trainer()
            losses = [tr.step(x, t)["loss"]]
            pids = tr.stage_pids()
            time.sleep(0.5)  # checkpoint message lands

            def killer():
                time.sleep(0.1)
                import os

                os.kill(pids[1], 9)

            th = threading.Thread(target=killer)
            th.start()
            completed = True
            try:
                for _ in range(3):
                    losses.append(tr.step(x, t)["loss"])
            except Exception:  # noqa: BLE001 — incl. any ObjectLostError
                completed = False
            th.join()
            time.sleep(1.2)
            st = rt.transfer_stats()
            tr.shutdown()
            return {"completed": completed, "steps": len(losses),
                    "stage_restarts": st["stage_restarts"]}
        finally:
            ray.shutdown()

    pairs = [one_round() for _ in range(rounds)]

    def pick(samples):
        best = max(samples, key=lambda s: s["tok_s"])
        return {**best, "samples": samples}

    fd, ofb = pick([p[0] for p in pairs]), pick([p[1] for p in pairs])
    try:
        chaos_row = chaos_round()
    except Exception as e:  # noqa: BLE001 — extra row must not kill A/B
        chaos_row = {"error": repr(e)}

    out = {"pp": pp, "microbatches": M, "tokens_per_step": batch * seq,
           "delay_ms": delay_ms, "rounds": rounds,
           "fill_drain": fd, "1f1b": ofb, "chaos": chaos_row}
    print(f"  [pipeline_train] 1f1b {ofb['tok_s']} tok/s vs fill_drain "
          f"{fd['tok_s']} tok/s "
          f"({ofb['tok_s'] / max(fd['tok_s'], 1e-9):.2f}x), bubble "
          f"{ofb['bubble_fraction']} vs {fd['bubble_fraction']}; chaos "
          f"completed={chaos_row.get('completed')} "
          f"(stage_restarts={chaos_row.get('stage_restarts')})",
          file=sys.stderr)
    return out


def impala_throughput_bench(iters=4):
    """Distributed IMPALA row: rollout workers -> aggregator actors ->
    the learner's host->device double-buffered queue, env-frames/s with
    the queue's measured occupancy, double-buffering on
    (``impala_queue_depth=2`` — the h2d of batch t+1 issues while the
    update for batch t computes) vs off (depth 0: direct per-update
    transfer), aggregators on in both modes so the only variable is
    the loader thread.  On CPU ``jnp.asarray`` is a near-free memcpy,
    so — like the shuffle row's paced pull plane — the shared
    ``_to_device`` hop is paced with a fixed per-batch delay modeling a
    real host->accelerator interconnect, applied identically in BOTH
    modes: depth 2 hides it behind the running update, depth 0 pays it
    serially, which makes the A/B load-independent."""
    import numpy as np  # noqa: F401 -- parity with workers

    pace_ms = 15

    def cartpole():
        import gymnasium

        return gymnasium.make("CartPole-v1")

    def one_mode(depth):
        import ray_tpu as ray
        from ray_tpu.rllib import ImpalaConfig
        from ray_tpu.rllib import impala as impala_mod

        real_to_device = impala_mod._to_device

        def paced_to_device(tm):
            time.sleep(pace_ms / 1000.0)
            return real_to_device(tm)

        impala_mod._to_device = paced_to_device
        ray.init(num_cpus=8, num_tpus=0,
                 _system_config={"impala_queue_depth": depth})
        try:
            config = (ImpalaConfig()
                      .environment(cartpole)
                      .rollouts(num_rollout_workers=2,
                                num_envs_per_worker=2,
                                rollout_fragment_length=32)
                      .training(lr=4e-3, num_aggregators=2,
                                max_batches_per_step=4))
            algo = config.build()
            algo.train()  # warm jit + fill the sample pipeline
            frames = 0
            t0 = time.perf_counter()
            for _ in range(iters):
                frames += algo.train()["num_env_steps_sampled"]
            dt = time.perf_counter() - t0
            q = (algo._h2d.queue_stats() if algo._h2d is not None
                 else {"gets": 0, "stalls": 0, "occupancy_avg": 0.0})
            algo.stop()
            return {"frames_s": round(frames / dt, 1),
                    "queue_depth": depth,
                    "queue_gets": q["gets"],
                    "queue_stalls": q["stalls"],
                    "queue_occupancy_avg": round(q["occupancy_avg"], 3)}
        finally:
            impala_mod._to_device = real_to_device
            ray.shutdown()

    def best_of(depth, rounds=3):
        samples = [one_mode(depth) for _ in range(rounds)]
        best = max(samples, key=lambda s: s["frames_s"])
        best["samples_frames_s"] = [s["frames_s"] for s in samples]
        return best

    on = best_of(2)
    off = best_of(0)
    out = {"h2d_pace_ms": pace_ms,
           "double_buffer_on": on, "double_buffer_off": off}
    print(f"  [impala_throughput] depth2 {on['frames_s']} frames/s "
          f"(occupancy {on['queue_occupancy_avg']}, stalls "
          f"{on['queue_stalls']}) vs depth0 {off['frames_s']} frames/s",
          file=sys.stderr)
    return out


def elastic_drill_bench():
    """Elastic-pods row: sustained small-task traffic against an
    autoscaled spot slice pool crosses ONE mid-run preemption — drain
    on (graceful notice: leases revoked, sole-copy results migrated,
    agent released cleanly) vs off (the same SIGUSR1 notice, but with
    ``elastic_drain=False`` the agent exits immediately — today's
    no-warning kill, lineage rebuilds).  Reports req/s and p99 task
    latency under the churn plus the drain/reconstruction counters;
    best-of-3 with raw per-round samples (PR 6/7 convention)."""
    import numpy as np  # noqa: F401 -- workers import it; keep parity

    import ray_tpu as ray
    from ray_tpu.autoscaler import FakeSliceProvider, StandardAutoscaler
    from ray_tpu.chaos import ChaosController
    from ray_tpu.cluster_utils import Cluster

    duration_s = 6.0

    @ray.remote(resources={"slice": 0.25}, max_retries=6)
    def work(i):
        import numpy as np

        # ~1.6 MB: over the inline cutoff, so results are node-store
        # homed — the sole-copy bytes the drain migrates (or, off, the
        # kill loses and lineage rebuilds).
        return np.full(200_000, i)

    def one_round(drain_on):
        sysconf = {} if drain_on else {"elastic_drain": False}
        c = Cluster(head_num_cpus=2, _system_config=sysconf)
        scaler = chaos = None
        try:
            provider = FakeSliceProvider(c, {
                "spot-v5e": {"resources": {"CPU": 2, "slice": 1},
                             "max_workers": 3, "spot": True}})
            scaler = StandardAutoscaler(c.rt, provider,
                                        idle_timeout_s=30.0,
                                        update_interval_s=0.4)
            scaler.start()
            chaos = ChaosController(c.rt)
            lat, held = [], {}
            ok = True
            t_start = time.perf_counter()
            t_end = t_start + duration_s
            preempt_at = t_end - duration_s / 2
            preempted = False
            i = 0
            while time.perf_counter() < t_end or not preempted:
                wave = {i + k: work.remote(i + k) for k in range(4)}
                i += 4
                t0 = time.perf_counter()
                vals = ray.get(list(wave.values()), timeout=120)
                lat.append((time.perf_counter() - t0) / len(wave))
                ok = ok and [int(v[0]) for v in vals] == list(wave)
                # every 4th wave's results are HELD unconsumed — the
                # sole-copy objects the preempted node must not lose
                if (i // 4) % 4 == 0:
                    held.update(wave)
                if not preempted and time.perf_counter() >= preempt_at:
                    preempted = chaos.preempt_node(notice=True) is not None
            for k, ref in held.items():
                v = ray.get(ref, timeout=120)
                ok = ok and int(v[0]) == k
            # Real elapsed, not the nominal window: the loop overruns
            # t_end when the preemption lands late, and that overrun
            # differs between modes — a fixed denominator would bias
            # the on/off comparison.
            elapsed = time.perf_counter() - t_start
            lat.sort()
            st = c.rt.transfer_stats()
            return {
                "req_per_s": round(i / elapsed, 1),
                "p50_ms": round(lat[len(lat) // 2] * 1e3, 1),
                "p99_ms": round(lat[max(0, int(len(lat) * 0.99) - 1)]
                                * 1e3, 1),
                "completed": ok and preempted,
                "drains_completed": st["drains_completed"],
                "objects_migrated": st["objects_migrated"],
                "reconstructions": st["reconstructions"],
            }
        finally:
            if chaos is not None:
                chaos.stop()
            if scaler is not None:
                scaler.stop()
            c.shutdown()

    def best_of(drain_on, rounds=3):
        samples = [one_round(drain_on) for _ in range(rounds)]
        best = min(samples, key=lambda s: (not s["completed"],
                                           s["p99_ms"]))
        return {**best, "samples": samples}

    out = {"duration_s": duration_s,
           "drain_on": best_of(True),
           "drain_off": best_of(False)}
    on, off = out["drain_on"], out["drain_off"]
    print(f"  [elastic] on: {on['req_per_s']} req/s p99 {on['p99_ms']}ms"
          f" migrated={on['objects_migrated']} rebuilds="
          f"{on['reconstructions']}; off: {off['req_per_s']} req/s p99 "
          f"{off['p99_ms']}ms rebuilds={off['reconstructions']}",
          file=sys.stderr)
    return out


def head_restart_blip_bench():
    """Head-failover row: sustained small-task traffic from a client
    crosses a hard head SIGKILL + restart (external-head cluster, one
    2-CPU agent).  Reports per-op p50/p99 latency, the blip duration
    (longest completion gap), and whether every get returned correctly
    — failover ON vs OFF.  The OFF run documents today's outage (the
    agent tears its workers down and post-restart gets fail), so the
    row keeps both the subsystem's cost and its value in the
    trajectory.  Best-of-3 with raw samples (PR 6/7 convention)."""
    import ray_tpu as ray
    from ray_tpu.cluster_utils import Cluster

    @ray.remote
    def _inc(x):
        return x + 1

    def one_round(failover):
        env = {} if failover else {"RAY_TPU_AGENT_RECONNECT": "0"}
        sysconf = {} if failover else {"head_failover": False}
        get_timeout = 30 if failover else 8
        c = Cluster(external_head=True, head_num_cpus=0,
                    _system_config=sysconf)
        try:
            c.add_node(num_cpus=2, external=True, env_overrides=env)
            ray.get([_inc.remote(i) for i in range(8)], timeout=60)
            lat, completions = [], []
            errors = 0
            killed = restarted = False
            t_start = time.time()
            t_end = t_start + 6.0
            i = 0
            while time.time() < t_end:
                t0 = time.perf_counter()
                try:
                    assert ray.get(_inc.remote(i),
                                   timeout=get_timeout) == i + 1
                    lat.append(time.perf_counter() - t0)
                    completions.append(time.time())
                except Exception:
                    errors += 1
                i += 1
                now = time.time() - t_start
                if not killed and now > 1.5:
                    c.kill_head()
                    killed = True
                elif killed and not restarted and now > 2.0:
                    c.restart_head()
                    restarted = True
                time.sleep(0.005)
            lat.sort()
            gaps = [b - a for a, b in zip(completions, completions[1:])]
            post_blip = [t for t in completions if t - t_start > 2.5]
            return {
                "ops": len(lat), "errors": errors,
                "p50_ms": (round(lat[len(lat) // 2] * 1e3, 2)
                           if lat else None),
                "p99_ms": (round(lat[min(len(lat) - 1,
                                         int(len(lat) * 0.99))] * 1e3, 2)
                           if lat else None),
                "blip_s": round(max(gaps), 2) if gaps else None,
                "completed": errors == 0 and bool(post_blip),
            }
        finally:
            c.shutdown()

    def best_of(failover, rounds=3):
        samples = [one_round(failover) for _ in range(rounds)]
        best = min(samples, key=lambda s: (not s["completed"],
                                           s["blip_s"] or 1e9))
        return {**best, "samples": samples}

    out = {"failover_on": best_of(True),
           "failover_off": best_of(False)}
    on, off = out["failover_on"], out["failover_off"]
    print(f"  [head_restart_blip] on: blip {on['blip_s']}s, p99 "
          f"{on['p99_ms']}ms, errors={on['errors']}, completed="
          f"{on['completed']}; off: errors={off['errors']}, completed="
          f"{off['completed']}", file=sys.stderr)
    return out


# Peak bf16 FLOP/s by device kind (for MFU).
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
    "TPU v6e": 918e12,
}


def tpu_bench():
    """Device-compute benchmarks on the real chip.  Returns {} off-TPU."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        print("  [tpu] no TPU backend; skipping device bench", file=sys.stderr)
        return {}

    dev = jax.devices()[0]
    peak = _PEAK_FLOPS.get(dev.device_kind, 197e12)
    out = {"device_kind": dev.device_kind, "peak_bf16_flops": peak}

    import numpy as np

    from ray_tpu.ops.attention import flash_attention, mha_reference

    # Per-call host timing is unreliable through the remote-device tunnel
    # (dispatch is async, sync fetches pay an RTT), so every measurement
    # chains N dependent steps inside ONE jitted scan and divides.
    def time_chained(attn, q, k, v, iters):
        @jax.jit
        def chain(q, k, v):
            def loss(qq):
                return attn(qq, k, v, causal=True).astype(jnp.float32).sum()

            def body(c, _):
                val, g = jax.value_and_grad(loss)(c)
                return (c + 1e-6 * g.astype(c.dtype)), val

            c, vals = jax.lax.scan(body, q, None, length=iters)
            return c[0, 0, 0, 0] + vals.sum()

        np.asarray(chain(q, k, v))  # compile + warm
        t0 = time.perf_counter()
        np.asarray(chain(q, k, v))
        return (time.perf_counter() - t0) / iters

    # Flash attention fwd+bwd vs the XLA reference, bf16 shapes.  d=64
    # keys keep their round-3/4 names for cross-round comparison; d=128
    # is the FLAGSHIP geometry (head_dim=128, __graft_entry__).
    for (h, d) in ((16, 64), (8, 128)):
        tag = "" if d == 64 else f"_d{d}"
        b = 4
        for seq in (2048, 8192):
            key = jax.random.PRNGKey(0)
            q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                         (b, seq, h, d),
                                         dtype=jnp.bfloat16)
                       for i in range(3))
            t_flash = time_chained(flash_attention, q, k, v, 16)
            # fwd 4*b*h*s^2*d + bwd 2x = 12 (full, non-causal count).
            flops = 12 * b * h * seq * seq * d
            out[f"flash_attn{tag}_s{seq}_ms"] = round(t_flash * 1e3, 3)
            out[f"flash_attn{tag}_s{seq}_tflops"] = round(
                flops / t_flash / 1e12, 1)
            extra = ""
            if seq <= 2048:
                # The XLA reference materializes (s, s) scores — OOMs at
                # 8k; its existence at 2k is the speedup context.
                t_ref = time_chained(mha_reference, q, k, v, 16)
                out[f"flash_attn{tag}_s{seq}_vs_xla"] = round(
                    t_ref / t_flash, 3)
                extra = f", {t_ref/t_flash:.2f}x XLA ref"
            try:
                # jax's own pallas TPU flash kernel on the same shapes —
                # the strongest public baseline for this op.
                from jax.experimental.pallas.ops.tpu.flash_attention \
                    import flash_attention as jax_flash

                def jx(qq, kk, vv, causal=True):
                    tq = jnp.transpose(qq, (0, 2, 1, 3))
                    tk = jnp.transpose(kk, (0, 2, 1, 3))
                    tv = jnp.transpose(vv, (0, 2, 1, 3))
                    o = jax_flash(tq, tk, tv, causal=causal,
                                  sm_scale=qq.shape[-1] ** -0.5)
                    return jnp.transpose(o, (0, 2, 1, 3))

                t_jax = time_chained(jx, q, k, v, 16)
                out[f"flash_attn{tag}_s{seq}_vs_jax_pallas"] = round(
                    t_jax / t_flash, 3)
                extra += f", {t_jax/t_flash:.2f}x jax-pallas"
            except Exception:
                pass
            print(f"  [tpu] flash d={d} s={seq}: {t_flash*1e3:.2f}ms "
                  f"({flops/t_flash/1e12:.1f} TF/s full-count{extra})",
                  file=sys.stderr)

    # Train steps: flagship (162M, round-comparable keys) and a ~1.2B
    # config where HBM is actually tight on one chip — remat + donation
    # + bf16 params/optimizer are what make it fit (BASELINE.json
    # north-star direction; reference scale context:
    # release/alpa_tests/train_opt_2_7b_minimum.py).
    import optax

    from __graft_entry__ import _flagship_cfg
    from ray_tpu.models import LlamaConfig
    from ray_tpu.train import init_train_state, make_train_step

    def train_bench(prefix, cfg, batch, iters):
        seq = cfg.max_seq_len
        opt = optax.adamw(1e-3)
        state = init_train_state(jax.random.PRNGKey(0), cfg, opt)
        step = make_train_step(cfg, opt, donate=False)
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, seq + 1), 0,
                                    cfg.vocab_size, dtype=jnp.int32)

        from functools import partial

        # State buffers are donated: XLA updates params/opt state in
        # place across the whole scan instead of double-buffering ~3x
        # param bytes — this is what lets the 1.2B config fit.
        @partial(jax.jit, donate_argnums=(0,))
        def run(state, tokens):
            def body(s, _):
                s2, m = step(s, {"tokens": tokens})
                return s2, m["loss"]
            return jax.lax.scan(body, state, None, length=iters)

        state, losses = run(state, tokens)   # compile + warm
        np.asarray(losses)
        t0 = time.perf_counter()
        state, losses = run(state, tokens)
        np.asarray(losses)
        dt = (time.perf_counter() - t0) / iters

        n_params = sum(x.size
                       for x in jax.tree_util.tree_leaves(state.params))
        toks = batch * seq
        # 6N per token (fwd+bwd matmuls) + attention 12*L*s*h*d/token.
        step_flops = toks * (6 * n_params
                             + 12 * cfg.num_layers * seq * cfg.num_heads
                             * cfg.head_dim)
        mfu = step_flops / dt / peak
        out[f"{prefix}_step_ms"] = round(dt * 1e3, 2)
        out[f"{prefix}_tokens_per_s"] = round(toks / dt)
        out[f"{prefix}_mfu"] = round(mfu, 4)
        # Full-layer remat (measured faster than both no-remat and
        # selective policies on v5e): the device EXECUTES ~8N/6N of the
        # counted FLOPs; this is the hardware-utilization number the
        # counted MFU hides.
        out[f"{prefix}_util_with_remat"] = round(mfu * 8.0 / 6.0, 4)
        out[f"{prefix}_params_m"] = round(n_params / 1e6, 1)
        print(f"  [tpu] {prefix} step: {dt*1e3:.1f}ms, "
              f"{toks/dt:,.0f} tok/s, MFU {mfu*100:.1f}% "
              f"({n_params/1e6:.0f}M params, {dev.device_kind})",
              file=sys.stderr)
        del state, tokens

    train_bench("train", _flagship_cfg(), batch=16, iters=10)
    out["model_params_m"] = out.pop("train_params_m")  # legacy key
    try:
        # param_dtype=bf16: 1.2B params = 2.4GB + adam mu/nu 4.8GB —
        # fp32 masters (14.4GB state) would not leave room for
        # activations on a 16GB v5e chip.
        cfg_1b = LlamaConfig(
            vocab_size=32000, embed_dim=2048, num_layers=16,
            num_heads=16, num_kv_heads=16, head_dim=128, mlp_dim=8192,
            max_seq_len=2048, dtype=jnp.bfloat16,
            param_dtype=jnp.bfloat16, attn_impl="flash", remat=True)
        train_bench("train_1b", cfg_1b, batch=8, iters=4)
    except Exception as e:  # noqa: BLE001 — 1B row must not kill bench
        out["train_1b_error"] = repr(e)[:300]
        print(f"  [tpu] train_1b failed: {e!r}", file=sys.stderr)
    return out


def main():
    results, raw_samples = core_bench()

    ratios = []
    extras = {}
    for k, v in results.items():
        r = v / BASELINE[k]
        tag = ""
        if k in NON_COMPARABLE:
            extras[k] = {"value": round(v, 1), "ref": BASELINE[k],
                         "ratio": round(r, 2),
                         "note": "excluded from geomean (not like-for-like)"}
            tag = "  [excluded from geomean]"
        else:
            ratios.append(r)
        print(f"  {k}: {v:.1f} (ref {BASELINE[k]:.1f}, {r:.2f}x){tag}",
              file=sys.stderr)

    def geomean(rs):
        g = 1.0
        for r in rs:
            g *= r
        return g ** (1.0 / len(rs))

    geo = geomean(ratios)
    # Transparency figure: every per-metric win clipped at 4x, so one
    # architecture-advantage outlier cannot carry the headline.
    geo_capped = geomean([min(r, 4.0) for r in ratios])

    try:
        locality = locality_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [locality] bench failed: {e!r}", file=sys.stderr)
        locality = {"error": repr(e)}

    try:
        data_streaming = data_streaming_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [data_streaming] bench failed: {e!r}", file=sys.stderr)
        data_streaming = {"error": repr(e)}

    try:
        serve_latency = serve_latency_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [serve] bench failed: {e!r}", file=sys.stderr)
        serve_latency = {"error": repr(e)}

    try:
        recovery = recovery_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [recovery] bench failed: {e!r}", file=sys.stderr)
        recovery = {"error": repr(e)}

    try:
        head_restart_blip = head_restart_blip_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [head_restart_blip] bench failed: {e!r}",
              file=sys.stderr)
        head_restart_blip = {"error": repr(e)}

    try:
        elastic_drill = elastic_drill_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [elastic_drill] bench failed: {e!r}", file=sys.stderr)
        elastic_drill = {"error": repr(e)}

    try:
        degraded_link = degraded_link_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [degraded_link] bench failed: {e!r}", file=sys.stderr)
        degraded_link = {"error": repr(e)}

    try:
        push_shuffle = shuffle_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [shuffle] bench failed: {e!r}", file=sys.stderr)
        push_shuffle = {"error": repr(e)}

    try:
        pipeline_train = pipeline_train_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [pipeline_train] bench failed: {e!r}", file=sys.stderr)
        pipeline_train = {"error": repr(e)}

    try:
        impala_throughput = impala_throughput_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [impala_throughput] bench failed: {e!r}",
              file=sys.stderr)
        impala_throughput = {"error": repr(e)}

    try:
        disagg_serving = disagg_serving_bench()
    except Exception as e:  # noqa: BLE001 — extra row must not kill core
        print(f"  [disagg_serving] bench failed: {e!r}",
              file=sys.stderr)
        disagg_serving = {"error": repr(e)}

    try:
        tpu = tpu_bench()
    except Exception as e:  # noqa: BLE001 — device bench must not kill core
        print(f"  [tpu] device bench failed: {e!r}", file=sys.stderr)
        tpu = {"error": repr(e)}

    print(json.dumps({
        "metric": "core_microbench_geomean_vs_reference",
        "value": round(geo, 4),
        "unit": "x (1.0 = reference-published parity)",
        "vs_baseline": round(geo, 4),
        "geomean_wins_capped_at_4x": round(geo_capped, 4),
        "contended_row_samples": raw_samples,
        "non_comparable": extras,
        "arg_locality": locality,
        "data_streaming": data_streaming,
        "recovery": recovery,
        "head_restart_blip": head_restart_blip,
        "elastic_drill": elastic_drill,
        "degraded_link": degraded_link,
        "serve_latency": serve_latency,
        "push_shuffle": push_shuffle,
        # Last (before the small tpu dict): the round artifact keeps the
        # TAIL of this line, and this round's A/B rows live here.
        "pipeline_train": pipeline_train,
        "impala_throughput": impala_throughput,
        "disagg_serving": disagg_serving,
        "tpu": tpu,
    }))


if __name__ == "__main__":
    main()
